"""BERT/ERNIE encoder family (BASELINE configs 3-4).

Mirrors the GPT distributed test pattern: training convergence, tp
parity, ZeRO-2 + AMP (the ERNIE-large fleet config) parity.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    bert_pretrain_loss_fn, ernie_large)
from paddle_tpu.parallel import (ShardedTrainStep, ShardingStage,
                                 build_mesh, set_global_mesh)


def _cfg(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                max_position=32)
    base.update(kw)
    return BertConfig(**base)


def _batch(rng, B=8, T=16, vocab=128):
    x = rng.randint(0, vocab, (B, T))
    tt = rng.randint(0, 2, (B, T))
    mlm = np.full((B, T), -100, np.int64)
    mask = rng.rand(B, T) < 0.15
    mlm[mask] = x[mask]
    nsp = rng.randint(0, 2, (B,))
    return [paddle.to_tensor(a) for a in (x, tt, mlm, nsp)]


def test_bert_pretraining_loss_decreases():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = BertForPretraining(_cfg())
    optim = opt.AdamW(1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, bert_pretrain_loss_fn, optim)
    batch = _batch(rng)
    losses = [float(step(*batch).numpy()) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.8


def test_bert_mlm_loss_matches_masked_oracle():
    """MLM loss == mean CE over ONLY the masked (label != -100)
    positions, plus the NSP CE — checked against a numpy oracle."""
    paddle.seed(1)
    rng = np.random.RandomState(1)
    model = BertForPretraining(_cfg())
    x, tt, mlm, nsp = _batch(rng)
    got = float(model.loss(x, tt, mlm, nsp).numpy())

    logits, nsp_logits = model(x, tt)
    lg = logits.numpy().reshape(-1, 128).astype(np.float64)
    lab = mlm.numpy().reshape(-1)
    logp = lg - np.log(np.exp(lg - lg.max(1, keepdims=True)).sum(1,
                       keepdims=True)) - lg.max(1, keepdims=True)
    sel = lab != -100
    mlm_oracle = -logp[sel, lab[sel]].mean()
    ng = nsp_logits.numpy().astype(np.float64)
    nlogp = ng - np.log(np.exp(ng - ng.max(1, keepdims=True)).sum(
        1, keepdims=True)) - ng.max(1, keepdims=True)
    nsp_oracle = -nlogp[np.arange(len(ng)), nsp.numpy()].mean()
    np.testing.assert_allclose(got, mlm_oracle + nsp_oracle, rtol=1e-5)


def test_bert_tp_matches_single_device():
    """Megatron-sharded encoder (tp=2) reproduces the 1-device losses —
    the BASELINE config-3 fleet path."""
    rng = np.random.RandomState(2)
    batches = [_batch(rng) for _ in range(3)]

    def run(tp):
        mesh = build_mesh(dp=1, pp=1, tp=tp, sp=1, sharding=8 // tp if tp > 1 else 1)
        set_global_mesh(mesh)
        paddle.seed(0)
        model = BertForPretraining(_cfg())
        optim = opt.AdamW(1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, bert_pretrain_loss_fn, optim,
                                mesh=mesh)
        return [float(step(*b).numpy()) for b in batches]

    tp2 = run(2)
    mesh1 = build_mesh(dp=1, pp=1, tp=1, sp=1, sharding=1,
                       devices=[__import__("jax").devices()[0]])
    set_global_mesh(mesh1)
    paddle.seed(0)
    model = BertForPretraining(_cfg())
    optim = opt.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, bert_pretrain_loss_fn, optim,
                            mesh=mesh1)
    single = [float(step(*b).numpy()) for b in batches]
    np.testing.assert_allclose(tp2, single, rtol=2e-3, atol=2e-3)


def test_ernie_config_zero2_amp_runs():
    """BASELINE config 4: ERNIE-architecture model under ZeRO-2 sharding
    + AMP O2 — the fleet sharding meta-optimizer path, tiny-sized."""
    mesh = build_mesh(dp=1, pp=1, tp=2, sp=1, sharding=4)
    set_global_mesh(mesh)
    paddle.seed(0)
    cfg = ernie_large()
    cfg.vocab_size, cfg.hidden_size, cfg.num_layers, cfg.num_heads = \
        128, 64, 2, 4
    cfg.max_position = 32
    model = BertForPretraining(cfg)
    optim = opt.AdamW(1e-3, parameters=model.parameters())
    model, optim = paddle.amp.decorate(model, optim, level="O2",
                                       dtype="bfloat16")
    step = ShardedTrainStep(model, bert_pretrain_loss_fn, optim,
                            mesh=mesh,
                            sharding_stage=ShardingStage.GRADIENT)
    rng = np.random.RandomState(3)
    batch = _batch(rng)
    l0 = float(step(*batch).numpy())
    l1 = float(step(*batch).numpy())
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0  # same batch twice: must improve


def test_masked_positions_path_matches_full_logits():
    """The gathered MLM head (reference bert_dygraph_model.py:335: gather
    mask_pos before PretrainingHeads) must produce exactly the full-logits
    rows at those positions, and the same loss as the dense ignore_index
    formulation when every sample masks the same count."""
    paddle.seed(0)
    rng = np.random.RandomState(1)
    model = BertForPretraining(_cfg())
    model.eval()
    B, T, P = 4, 16, 3
    x = paddle.to_tensor(rng.randint(0, 128, (B, T)))
    tt = paddle.to_tensor(rng.randint(0, 2, (B, T)))
    pos = np.stack([rng.choice(T, P, replace=False) for _ in range(B)])
    pos.sort(axis=1)
    pos_t = paddle.to_tensor(pos.astype(np.int32))
    full, _ = model(x, tt)
    gathered, _ = model(x, tt, masked_positions=pos_t)
    fg = np.take_along_axis(full.numpy(), pos[..., None], axis=1)
    np.testing.assert_allclose(gathered.numpy(), fg, rtol=1e-5, atol=1e-5)

    labels = rng.randint(0, 128, (B, P)).astype(np.int64)
    dense = np.full((B, T), -100, np.int64)
    np.put_along_axis(dense, pos, labels, axis=1)
    nsp = paddle.to_tensor(rng.randint(0, 2, (B,)))
    l_gather = model.loss(x, tt, paddle.to_tensor(labels), nsp,
                          masked_positions=pos_t)
    l_dense = model.loss(x, tt, paddle.to_tensor(dense), nsp)
    np.testing.assert_allclose(float(l_gather.numpy()),
                               float(l_dense.numpy()), rtol=1e-5)
