"""nn layer/functional tests (reference test analogues:
python/paddle/fluid/tests/unittests/test_layers.py, test_conv2d_op.py,
test_batch_norm_op.py, test_transformer_api.py, test_rnn_*.py — here
checked against torch CPU as the numeric oracle, the same role the
reference's numpy reference implementations play in OpTest)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

import jax  # noqa: E402

# vs-torch-CPU tolerances: TPU hardware transcendentals (erf/tanh/exp
# approximations) and float reassociation differ from torch's CPU libm
# at the 1e-5 level (measured: activations 2.2e-05 max abs, pooling
# 1.3e-08 under a strict-equal default), so the real-chip lane runs the
# same oracles at a looser tolerance
_ATOL = 1e-4 if jax.default_backend() == "tpu" else 1e-5
_RTOL = 1e-3 if jax.default_backend() == "tpu" else 1e-4


def test_linear_matches_torch():
    x = np.random.randn(4, 6).astype("float32")
    w = np.random.randn(6, 3).astype("float32")
    b = np.random.randn(3).astype("float32")
    out = F.linear(paddle.to_tensor(x), paddle.to_tensor(w),
                   paddle.to_tensor(b)).numpy()
    ref = tF.linear(torch.tensor(x), torch.tensor(w.T),
                    torch.tensor(b)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("stride,padding,dilation,groups", [
    (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 3),
])
def test_conv2d_matches_torch(stride, padding, dilation, groups):
    cin, cout = 6, 9
    x = np.random.randn(2, cin, 10, 10).astype("float32")
    w = np.random.randn(cout, cin // groups, 3, 3).astype("float32")
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), None,
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups).numpy()
    ref = tF.conv2d(torch.tensor(x), torch.tensor(w), None, stride=stride,
                    padding=padding, dilation=dilation,
                    groups=groups).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_conv2d_grad_matches_torch():
    x = np.random.randn(2, 3, 8, 8).astype("float32")
    w = np.random.randn(4, 3, 3, 3).astype("float32")
    px = paddle.to_tensor(x, stop_gradient=False)
    pw = paddle.to_tensor(w, stop_gradient=False)
    F.conv2d(px, pw, padding=1).sum().backward()
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tF.conv2d(tx, tw, padding=1).sum().backward()
    np.testing.assert_allclose(px.grad.numpy(), tx.grad.numpy(), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(pw.grad.numpy(), tw.grad.numpy(), rtol=1e-3,
                               atol=1e-4)


def test_conv_transpose_matches_torch():
    x = np.random.randn(2, 3, 8, 8).astype("float32")
    w = np.random.randn(3, 5, 4, 4).astype("float32")
    out = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             stride=2, padding=1).numpy()
    ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)


def test_pooling_matches_torch():
    x = np.random.randn(2, 3, 8, 8).astype("float32")
    np.testing.assert_allclose(
        F.max_pool2d(paddle.to_tensor(x), 2, 2).numpy(),
        tF.max_pool2d(torch.tensor(x), 2, 2).numpy())
    np.testing.assert_allclose(
        F.avg_pool2d(paddle.to_tensor(x), 3, 2, 1).numpy(),
        tF.avg_pool2d(torch.tensor(x), 3, 2, 1,
                      count_include_pad=False).numpy(), rtol=1e-5,
        atol=1e-6)  # measured TPU deviation 1.3e-08; keep a tight oracle
    np.testing.assert_allclose(
        F.adaptive_avg_pool2d(paddle.to_tensor(x), 3).numpy(),
        tF.adaptive_avg_pool2d(torch.tensor(x), 3).numpy(), rtol=1e-4,
        atol=1e-5)


def test_norms_match_torch():
    x = np.random.randn(4, 6, 5, 5).astype("float32")
    g = np.random.rand(6).astype("float32") + 0.5
    b = np.random.randn(6).astype("float32")
    out = F.group_norm(paddle.to_tensor(x), 3, 1e-5, paddle.to_tensor(g),
                       paddle.to_tensor(b)).numpy()
    ref = tF.group_norm(torch.tensor(x), 3, torch.tensor(g),
                        torch.tensor(b)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    out = F.instance_norm(paddle.to_tensor(x), weight=paddle.to_tensor(g),
                          bias=paddle.to_tensor(b)).numpy()
    ref = tF.instance_norm(torch.tensor(x), weight=torch.tensor(g),
                           bias=torch.tensor(b)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


def test_batch_norm_train_and_eval():
    bn = nn.BatchNorm2D(3, momentum=0.9)
    x = np.random.randn(8, 3, 4, 4).astype("float32")
    tb = torch.nn.BatchNorm2d(3, momentum=0.1)
    out = bn(paddle.to_tensor(x)).numpy()
    ref = tb(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(bn._mean.numpy(), tb.running_mean.numpy(),
                               rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(bn._variance.numpy(),
                               tb.running_var.numpy(), rtol=1e-3, atol=1e-4)
    bn.eval()
    tb.eval()
    out = bn(paddle.to_tensor(x)).numpy()
    ref = tb(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


def test_fused_bn_act_matches_composed():
    """batch_norm_act (residual-light fused bn+(add+)relu, the
    fuse_bn_act_pass.cc / fused_bn_add_activation_op.cc analogue) must
    match composed bn -> (+z) -> relu in outputs, grads and running
    stats."""
    np.random.seed(7)
    x_np = np.random.randn(4, 6, 5, 5).astype("float32")
    z_np = np.random.randn(4, 6, 5, 5).astype("float32")
    w_np = (np.random.rand(6) + 0.5).astype("float32")
    b_np = (np.random.randn(6) * 0.1).astype("float32")

    for use_add in (False, True):
        ts = []
        for fused in (False, True):
            x = paddle.to_tensor(x_np); x.stop_gradient = False
            z = paddle.to_tensor(z_np); z.stop_gradient = False
            w = paddle.to_tensor(w_np); w.stop_gradient = False
            b = paddle.to_tensor(b_np); b.stop_gradient = False
            rm = paddle.to_tensor(np.zeros(6, "float32"))
            rv = paddle.to_tensor(np.ones(6, "float32"))
            if fused:
                out = F.batch_norm_act(x, rm, rv, w, b, training=True,
                                       add=z if use_add else None)
            else:
                out = F.batch_norm(x, rm, rv, w, b, training=True)
                if use_add:
                    out = out + z
                out = F.relu(out)
            (out * out).sum().backward()
            ts.append((out, x.grad, z.grad if use_add else None,
                       w.grad, b.grad, rm, rv))
        for a, bb in zip(ts[0], ts[1]):
            if a is None:
                assert bb is None
                continue
            np.testing.assert_allclose(a.numpy(), bb.numpy(),
                                       rtol=2e-5, atol=2e-5)
    # eval mode goes through the inference path
    bn_args = (paddle.to_tensor(np.zeros(6, "float32")),
               paddle.to_tensor(np.ones(6, "float32")))
    xe = paddle.to_tensor(x_np)
    fe = F.batch_norm_act(xe, *bn_args, paddle.to_tensor(w_np),
                          paddle.to_tensor(b_np), training=False)
    ce = F.relu(F.batch_norm(xe, *bn_args, paddle.to_tensor(w_np),
                             paddle.to_tensor(b_np), training=False))
    np.testing.assert_allclose(fe.numpy(), ce.numpy(), rtol=1e-6)


def test_resnet_blocks_custom_norm_and_frozen_stats():
    """the fused bn+relu fast path must not hijack custom norm layers or
    frozen-stats BN (use_global_stats=True keeps running stats untouched
    and normalizes with them even in train mode)."""
    import functools
    from paddle_tpu.vision.models.resnet import BottleneckBlock
    # custom norm layer: GroupNorm has none of BatchNorm's private attrs
    blk = BottleneckBlock(64, 16, norm_layer=lambda c: nn.GroupNorm(4, c))
    out = blk(paddle.to_tensor(np.random.randn(2, 64, 8, 8).astype("float32")))
    assert out.shape == [2, 64, 8, 8]
    # frozen-stats BN: running stats must survive a train-mode forward
    frozen = functools.partial(nn.BatchNorm2D, use_global_stats=True)
    blk2 = BottleneckBlock(64, 16, norm_layer=frozen)
    rm_before = blk2.bn1._mean.numpy().copy()
    blk2.train()
    blk2(paddle.to_tensor(np.random.randn(2, 64, 8, 8).astype("float32")))
    np.testing.assert_array_equal(blk2.bn1._mean.numpy(), rm_before)


def test_fused_bn_act_explicit_false_global_stats_in_eval():
    """use_global_stats=False is NOT the same as None: in eval mode it
    still normalizes with batch stats and updates the EMA (batch_norm
    semantics). The fused path must match the composed path exactly."""
    np.random.seed(3)
    x_np = np.random.randn(4, 6, 5, 5).astype("float32") + 2.0
    outs, stats = [], []
    for fused in (False, True):
        rm = paddle.to_tensor(np.zeros(6, "float32"))
        rv = paddle.to_tensor(np.ones(6, "float32"))
        x = paddle.to_tensor(x_np)
        if fused:
            out = F.batch_norm_act(x, rm, rv, training=False,
                                   use_global_stats=False)
        else:
            out = F.relu(F.batch_norm(x, rm, rv, training=False,
                                      use_global_stats=False))
        outs.append(out.numpy())
        stats.append((rm.numpy(), rv.numpy()))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)
    for a, b in zip(stats[0], stats[1]):
        np.testing.assert_allclose(a, b, rtol=1e-6)
        assert not np.allclose(a, 0.0) or not np.allclose(b, 1.0)
    # and the EMA actually moved (mean shifted toward the +2 batch mean)
    assert stats[0][0].mean() > 0.05


def test_fused_bn_act_broadcastable_add_backward():
    """batch_norm_act with a broadcastable residual (e.g. a per-channel
    bias [1, C, 1, 1]) must reduce the z-cotangent to z's shape instead of
    crashing in the custom-vjp backward."""
    np.random.seed(4)
    x_np = np.random.randn(4, 6, 5, 5).astype("float32")
    z_np = np.random.randn(1, 6, 1, 1).astype("float32")
    grads = []
    for fused in (False, True):
        x = paddle.to_tensor(x_np); x.stop_gradient = False
        z = paddle.to_tensor(z_np); z.stop_gradient = False
        rm = paddle.to_tensor(np.zeros(6, "float32"))
        rv = paddle.to_tensor(np.ones(6, "float32"))
        if fused:
            out = F.batch_norm_act(x, rm, rv, training=True, add=z)
        else:
            out = F.relu(F.batch_norm(x, rm, rv, training=True) + z)
        (out * out).sum().backward()
        grads.append((x.grad.numpy(), z.grad.numpy()))
    assert grads[1][1].shape == z_np.shape
    np.testing.assert_allclose(grads[0][0], grads[1][0], rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(grads[0][1], grads[1][1], rtol=2e-5,
                               atol=2e-4)


def test_attention_path_routing_by_seq_len():
    """Short sequences must route to the composed path DELIBERATELY (no
    fallback warning): at T=128/d=64 the flash custom-call's layout copies
    cost more than the tiny score matrix saves (BERT-base measured +71%
    composed on v5e). Long sequences keep trying flash."""
    import warnings
    from paddle_tpu.nn.functional import attention as attn_mod
    q = paddle.to_tensor(np.random.randn(2, 128, 4, 64).astype("float32"))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no fallback warn
        out = F.scaled_dot_product_attention(q, q, q)
    assert attn_mod.LAST_PATH == "composed"
    assert out.shape == [2, 128, 4, 64]
    # below the threshold flag, flash is attempted (falls back loudly on
    # CPU where the pallas kernel is unsupported — that IS the warning
    # path, proving the attempt happened)
    from paddle_tpu.core import flags as _flags
    prev_min_seq = _flags.flag("flash_attention_min_seq")
    paddle.set_flags({"FLAGS_flash_attention_min_seq": 64})
    try:
        import jax
        attn_mod._warned_fallback = False
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            F.scaled_dot_product_attention(q, q, q)
        if jax.default_backend() != "tpu":
            assert attn_mod.LAST_PATH == "composed"
            assert any("flash attention kernel unavailable" in str(x.message)
                       for x in w)
        else:
            assert attn_mod.LAST_PATH == "flash"
    finally:
        paddle.set_flags({"FLAGS_flash_attention_min_seq": prev_min_seq})
        attn_mod._warned_fallback = False


def test_losses_match_torch():
    logits = np.random.randn(8, 5).astype("float32")
    labels = np.random.randint(0, 5, 8)
    np.testing.assert_allclose(
        F.cross_entropy(paddle.to_tensor(logits),
                        paddle.to_tensor(labels)).numpy(),
        tF.cross_entropy(torch.tensor(logits),
                         torch.tensor(labels)).numpy(), rtol=1e-5)
    x = np.random.rand(6).astype("float32")
    y = (np.random.rand(6) > 0.5).astype("float32")
    np.testing.assert_allclose(
        F.binary_cross_entropy(paddle.to_tensor(x),
                               paddle.to_tensor(y)).numpy(),
        tF.binary_cross_entropy(torch.tensor(x), torch.tensor(y)).numpy(),
        rtol=1e-4)
    lx = np.random.randn(6).astype("float32")
    np.testing.assert_allclose(
        F.binary_cross_entropy_with_logits(paddle.to_tensor(lx),
                                           paddle.to_tensor(y)).numpy(),
        tF.binary_cross_entropy_with_logits(torch.tensor(lx),
                                            torch.tensor(y)).numpy(),
        rtol=1e-5)
    a = np.random.randn(4, 7).astype("float32")
    b = np.random.randn(4, 7).astype("float32")
    np.testing.assert_allclose(
        F.smooth_l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        tF.smooth_l1_loss(torch.tensor(a), torch.tensor(b)).numpy(),
        rtol=1e-5)
    np.testing.assert_allclose(
        F.kl_div(paddle.to_tensor(a), paddle.to_tensor(np.abs(b))).numpy(),
        tF.kl_div(torch.tensor(a), torch.tensor(np.abs(b))).numpy(),
        rtol=1e-4, atol=1e-5)


def test_cross_entropy_ignore_index_and_weight():
    logits = np.random.randn(6, 4).astype("float32")
    labels = np.array([0, 1, -100, 3, -100, 2])
    w = np.random.rand(4).astype("float32") + 0.5
    out = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          weight=paddle.to_tensor(w)).numpy()
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                           weight=torch.tensor(w)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_activations_match_torch():
    x = np.random.randn(4, 8).astype("float32")
    cases = [
        (F.gelu, lambda t: tF.gelu(t)),
        (lambda v: F.gelu(v, approximate=True),
         lambda t: tF.gelu(t, approximate="tanh")),
        (F.silu, tF.silu),
        (F.softplus, tF.softplus),
        (F.elu, tF.elu),
        (F.selu, tF.selu),
        (F.hardswish, tF.hardswish),
        (F.mish, tF.mish),
        (lambda v: F.leaky_relu(v, 0.1),
         lambda t: tF.leaky_relu(t, 0.1)),
        (lambda v: F.log_softmax(v, -1),
         lambda t: tF.log_softmax(t, -1)),
    ]
    for mine, ref in cases:
        np.testing.assert_allclose(
            mine(paddle.to_tensor(x)).numpy(),
            ref(torch.tensor(x)).numpy(), rtol=_RTOL, atol=_ATOL)


def test_dropout_semantics():
    x = paddle.ones([1000])
    out = F.dropout(x, 0.5, training=True)
    kept = float((out.numpy() != 0).mean())
    assert 0.35 < kept < 0.65
    np.testing.assert_allclose(out.numpy()[out.numpy() != 0], 2.0)
    out_eval = F.dropout(x, 0.5, training=False)
    np.testing.assert_allclose(out_eval.numpy(), x.numpy())


def test_embedding_grad_and_padding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([[1, 0, 2]]))
    out = emb(ids)
    assert float(np.abs(out.numpy()[0, 1]).sum()) == 0.0
    out.sum().backward()
    g = emb.weight.grad.numpy()
    assert g[1].sum() != 0 and g[3].sum() == 0


def test_sdpa_matches_torch():
    q = np.random.randn(2, 8, 2, 16).astype("float32")
    k = np.random.randn(2, 8, 2, 16).astype("float32")
    v = np.random.randn(2, 8, 2, 16).astype("float32")
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True).numpy()
    tq, tk, tv = (torch.tensor(a).permute(0, 2, 1, 3) for a in (q, k, v))
    ref = tF.scaled_dot_product_attention(
        tq, tk, tv, is_causal=True).permute(0, 2, 1, 3).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_transformer_encoder_decoder():
    model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=64)
    src = paddle.randn([2, 6, 32])
    tgt = paddle.randn([2, 5, 32])
    out = model(src, tgt)
    assert out.shape == [2, 5, 32]
    out.mean().backward()
    assert all(p.grad is not None for p in model.parameters())


def test_rnn_shapes_and_grads():
    for cls, states in [(nn.SimpleRNN, 1), (nn.GRU, 1), (nn.LSTM, 2)]:
        m = cls(5, 7, num_layers=2)
        x = paddle.randn([3, 6, 5])
        y, final = m(x)
        assert y.shape == [3, 6, 7]
        y.sum().backward()
        assert all(p.grad is not None for p in m.parameters())


def test_lstm_cell_matches_torch():
    cell = nn.LSTMCell(4, 6)
    tcell = torch.nn.LSTMCell(4, 6)
    # copy weights
    cell.weight_ih.set_value(tcell.weight_ih.detach().numpy())
    cell.weight_hh.set_value(tcell.weight_hh.detach().numpy())
    cell.bias_ih.set_value(tcell.bias_ih.detach().numpy())
    cell.bias_hh.set_value(tcell.bias_hh.detach().numpy())
    x = np.random.randn(2, 4).astype("float32")
    h0 = np.random.randn(2, 6).astype("float32")
    c0 = np.random.randn(2, 6).astype("float32")
    _, (h, c) = cell(paddle.to_tensor(x),
                     (paddle.to_tensor(h0), paddle.to_tensor(c0)))
    th, tc = tcell(torch.tensor(x), (torch.tensor(h0), torch.tensor(c0)))
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(c.numpy(), tc.detach().numpy(), rtol=1e-4,
                               atol=1e-5)


def test_layer_hooks_and_apply():
    m = nn.Linear(3, 3)
    calls = []
    h = m.register_forward_post_hook(lambda l, i, o: calls.append(1))
    m(paddle.ones([2, 3]))
    assert calls
    h.remove()
    m(paddle.ones([2, 3]))
    assert len(calls) == 1
    m.eval()
    assert not m.training
    m.train()
    assert m.training


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4), nn.Linear(4, 2))
    m2 = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4), nn.Linear(4, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([3, 4])
    m1.eval()
    m2.eval()
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_clip_grad_by_global_norm():
    m = nn.Linear(3, 3)
    (m(paddle.ones([2, 3])) * 100).sum().backward()
    clip = nn.ClipGradByGlobalNorm(1.0)
    pg = clip([(p, p.grad) for p in m.parameters()])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in pg))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)


def test_weight_norm():
    from paddle_tpu.nn.utils import weight_norm, remove_weight_norm
    m = nn.Linear(4, 5)
    w0 = m.weight.numpy().copy()
    weight_norm(m, "weight")
    x = paddle.randn([2, 4])
    y1 = m(x).numpy()
    assert "weight_g" in dict(m.named_parameters())
    remove_weight_norm(m)
    y2 = m(x).numpy()
    np.testing.assert_allclose(y1, y2, rtol=1e-4, atol=1e-5)


def test_flash_lmdi_width1_patch_applies():
    """The vendored width-1 l/m/di rewrite must keep matching the upstream
    pallas flash kernel source (all guards hit); a False here means jax
    drifted and the bwd pass silently reverted to materialising 3x100MB
    broadcast copies per layer (or, worse, the fallback dq-di patch also
    stopped matching)."""
    from paddle_tpu.ops.pallas.flash_attention import _patch_lmdi_width1
    assert _patch_lmdi_width1() is True
