"""SelectedRows sparse-gradient tests.

Reference: framework/selected_rows.h + lookup_table_v2 grad is_sparse
branch + sgd_op.h/adam_op.h SelectedRows updates + merge_selected_rows /
get_tensor_from_selected_rows ops.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.core.selected_rows import (SelectedRows,
                                           get_tensor_from_selected_rows,
                                           merge_selected_rows)


def test_selected_rows_merge_and_dense():
    sr = SelectedRows(np.array([1, 3, 1]),
                      np.array([[1., 1.], [2., 2.], [3., 3.]], np.float32),
                      height=5)
    m = merge_selected_rows(sr)
    assert sorted(np.asarray(m.rows).tolist()) == [1, 3]
    d = get_tensor_from_selected_rows(sr)
    expected = np.zeros((5, 2), np.float32)
    expected[1] = [4, 4]
    expected[3] = [2, 2]
    np.testing.assert_allclose(d.numpy(), expected)


def test_sparse_embedding_grad_is_selected_rows():
    paddle.seed(0)
    vocab, dim = 100, 4
    w = paddle.to_tensor(np.random.randn(vocab, dim).astype("float32"),
                         stop_gradient=False)
    ids = paddle.to_tensor(np.array([[3, 7], [3, 9]]))
    out = F.embedding(ids, w, sparse=True)
    np.testing.assert_allclose(out.numpy()[0, 0], w.numpy()[3])
    out.sum().backward()
    g = w.grad._value
    assert isinstance(g, SelectedRows)
    assert g.values.shape[0] == 4  # batch*seq rows, NOT vocab rows
    dense = g.to_dense()
    # row 3 hit twice
    np.testing.assert_allclose(np.asarray(dense[3]), np.full(dim, 2.0))
    np.testing.assert_allclose(np.asarray(dense[7]), np.ones(dim))
    assert float(np.asarray(dense).sum()) == 4 * dim / 1


def test_sparse_grad_matches_dense_grad():
    paddle.seed(0)
    wn = np.random.randn(50, 3).astype("float32")
    ids = np.array([1, 4, 4, 9])

    w1 = paddle.to_tensor(wn, stop_gradient=False)
    F.embedding(paddle.to_tensor(ids), w1, sparse=True).sum().backward()
    w2 = paddle.to_tensor(wn, stop_gradient=False)
    F.embedding(paddle.to_tensor(ids), w2, sparse=False).sum().backward()
    np.testing.assert_allclose(np.asarray(w1.grad._value.to_dense()),
                               w2.grad.numpy(), rtol=1e-6)


@pytest.mark.parametrize("make_opt", [
    lambda params: opt.SGD(0.1, parameters=params),
    lambda params: opt.Momentum(0.1, parameters=params),
    lambda params: opt.Adam(0.1, parameters=params, lazy_mode=True),
], ids=["sgd", "momentum", "adam_lazy"])
def test_rowwise_update_matches_dense(make_opt):
    """Row-sliced sparse update == dense update for rows that were touched
    (lazy adam differs from dense adam on UNtouched rows by design)."""
    paddle.seed(0)
    wn = np.random.randn(20, 3).astype("float32")
    ids = np.array([2, 5, 5])

    w_s = paddle.to_tensor(wn.copy(), stop_gradient=False)
    o_s = make_opt([w_s])
    F.embedding(paddle.to_tensor(ids), w_s, sparse=True).sum().backward()
    o_s.step()

    w_d = paddle.to_tensor(wn.copy(), stop_gradient=False)
    o_d = make_opt([w_d])
    F.embedding(paddle.to_tensor(ids), w_d, sparse=False).sum().backward()
    o_d.step()

    touched = [2, 5]
    np.testing.assert_allclose(w_s.numpy()[touched],
                               w_d.numpy()[touched], rtol=1e-5)
    # untouched rows unchanged in the sparse run
    untouched = [i for i in range(20) if i not in touched]
    np.testing.assert_allclose(w_s.numpy()[untouched], wn[untouched])


def test_nonlazy_adam_densifies_correctly():
    """Non-lazy Adam must advance ALL moments → dense fallback, numerics
    equal to the dense-grad run."""
    paddle.seed(0)
    wn = np.random.randn(10, 2).astype("float32")
    ids = np.array([1, 3])

    w_s = paddle.to_tensor(wn.copy(), stop_gradient=False)
    o_s = opt.Adam(0.1, parameters=[w_s])  # lazy_mode=False
    F.embedding(paddle.to_tensor(ids), w_s, sparse=True).sum().backward()
    o_s.step()

    w_d = paddle.to_tensor(wn.copy(), stop_gradient=False)
    o_d = opt.Adam(0.1, parameters=[w_d])
    F.embedding(paddle.to_tensor(ids), w_d, sparse=False).sum().backward()
    o_d.step()
    np.testing.assert_allclose(w_s.numpy(), w_d.numpy(), rtol=1e-5)


def test_sparse_embedding_training_converges():
    """End to end: sparse-grad embedding + lazy adam learns a lookup."""
    paddle.seed(3)
    vocab, dim = 30, 8
    emb = paddle.to_tensor(
        (0.1 * np.random.randn(vocab, dim)).astype("float32"),
        stop_gradient=False)
    proj = paddle.to_tensor(np.random.randn(dim, 2).astype("float32"),
                            stop_gradient=False)
    optim = opt.Adam(0.05, parameters=[emb, proj], lazy_mode=True)
    ids = np.random.RandomState(0).randint(0, vocab, (64,))
    labels = (ids % 2).astype(np.int64)
    losses = []
    for _ in range(30):
        vec = F.embedding(paddle.to_tensor(ids), emb, sparse=True)
        logits = paddle.matmul(vec, proj)
        loss = F.cross_entropy(logits, paddle.to_tensor(labels))
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_padding_idx_rows_get_zero_grad():
    w = paddle.to_tensor(np.random.randn(10, 2).astype("float32"),
                         stop_gradient=False)
    ids = paddle.to_tensor(np.array([0, 3]))
    F.embedding(ids, w, padding_idx=0, sparse=True).sum().backward()
    dense = np.asarray(w.grad._value.to_dense())
    np.testing.assert_allclose(dense[0], np.zeros(2))
    np.testing.assert_allclose(dense[3], np.ones(2))


def test_sparse_with_master_weights_densifies_correctly():
    """amp O2 (fp32 master) + sparse grads: the master must stay
    authoritative, so the rowwise path defers to the dense update."""
    import jax.numpy as jnp
    wn = np.random.randn(10, 2).astype("float32")
    w_s = paddle.to_tensor(wn.copy(), stop_gradient=False)
    w_s._value = w_s._value.astype(jnp.bfloat16)
    o_s = opt.SGD(0.1, parameters=[w_s])
    o_s._multi_precision = True
    ids = np.array([1, 3])
    F.embedding(paddle.to_tensor(ids), w_s, sparse=True).sum().backward()
    o_s.step()
    o_s.clear_grad()
    st = o_s._accumulators[id(w_s)]
    # master advanced in fp32 from the bf16 starting point
    w0 = np.asarray(jnp.asarray(wn).astype(jnp.bfloat16).astype(
        jnp.float32))
    np.testing.assert_allclose(np.asarray(st["master"][1]), w0[1] - 0.1,
                               rtol=1e-2)
    F.embedding(paddle.to_tensor(ids), w_s, sparse=True).sum().backward()
    o_s.step()  # second step: master must include the first sparse update
    np.testing.assert_allclose(
        np.asarray(st["master"][1]) - np.asarray(
            o_s._accumulators[id(w_s)]["master"][1]), [0.1, 0.1], atol=1e-3)


def test_adamw_sparse_respects_decay_fn():
    wn = np.ones((6, 2), np.float32)
    w = paddle.to_tensor(wn.copy(), stop_gradient=False)
    w.name = "embedding_w"
    o = opt.AdamW(0.1, parameters=[w], weight_decay=0.5, lazy_mode=True,
                  apply_decay_param_fun=lambda n: n != "embedding_w")
    ids = np.array([0])
    F.embedding(paddle.to_tensor(ids), w, sparse=True).sum().backward()
    o.step()
    # row 0 moved by the adam update only; decay (×0.95) NOT applied
    # to untouched value portion: check untouched rows exactly unchanged,
    # and touched row shifted by ~lr (adam unit step), not scaled by 0.95
    np.testing.assert_allclose(w.numpy()[1:], wn[1:])
    assert abs(float(w.numpy()[0, 0]) - (1.0 - 0.1)) < 0.02
