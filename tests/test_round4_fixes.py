"""Round-4 correctness fixes (round-3 VERDICT weak #3/#5 + ADVICE items).

Oracles: closed-form math (prod/sign, Noam formula) and the reference
kernels' documented semantics (add_position_encoding_op.h, bbox_util.h
FilterBoxes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_allreduce_prod_handles_negatives_and_zeros():
    """reference c_allreduce_prod (c_allreduce_op.h:123): NCCL prod is
    sign-correct and zero-correct; exp(psum(log)) is not."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel import shard_map
    from paddle_tpu.distributed import ReduceOp
    from paddle_tpu.parallel.mesh import build_mesh, set_global_mesh

    mesh = build_mesh(dp=8, pp=1, tp=1, sp=1, sharding=1)
    set_global_mesh(mesh)

    def body(x):
        t = paddle.Tensor(x)
        dist.all_reduce(t, op=ReduceOp.PROD)
        return t._value

    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)
    # per-device columns: negatives, a zero, positives
    x = jnp.asarray([[-2.0, 1.0, 3.0],
                     [1.5, -1.0, 2.0],
                     [1.0, 2.0, 0.0],
                     [-1.0, 1.0, 1.0],
                     [2.0, 1.0, 1.0],
                     [1.0, -3.0, 2.0],
                     [1.0, 1.0, 1.0],
                     [-0.5, 2.0, 4.0]])
    out = np.asarray(f(x))
    expect = np.prod(np.asarray(x), axis=0)  # [-3.0, 12.0, 0.0] pattern
    for r in range(8):
        np.testing.assert_allclose(out[r], expect, rtol=1e-5)
    assert expect[0] < 0 and expect[2] == 0  # the case actually exercises it
    assert not np.any(np.isnan(out))

    # integer PROD must be exact (NCCL prod is; exp(psum(log)) truncates)
    xi = jnp.full((8, 1), 3, dtype=jnp.int32)
    outi = np.asarray(f(xi))
    assert outi.dtype == np.int32 and np.all(outi == 3 ** 8)


def test_add_position_encoding_small_feature_sizes():
    """reference add_position_encoding_op.h: half_size==1 uses pos/10000;
    odd feature size is rejected."""
    from paddle_tpu.ops import extra_ops

    x = np.zeros((1, 3, 2), np.float32)
    out = extra_ops.add_position_encoding(x, alpha=0.0, beta=1.0).numpy()
    pos = np.arange(3) / 10000.0
    np.testing.assert_allclose(out[0, :, 0], np.sin(pos), rtol=1e-6)
    np.testing.assert_allclose(out[0, :, 1], np.cos(pos), rtol=1e-6)

    with pytest.raises(ValueError):
        extra_ops.add_position_encoding(np.zeros((1, 2, 3), np.float32))


def test_noam_decay_matches_reference_formula():
    """reference lr.py:278 — a=1 at step 0 so lr(0)=0; thereafter
    min(step^-0.5, step*warmup^-1.5)."""
    sched = paddle.optimizer.lr.NoamDecay(d_model=64, warmup_steps=10,
                                          learning_rate=2.0)
    assert sched.get_lr() == 0.0
    vals = []
    for _ in range(15):
        sched.step()
        vals.append(sched.get_lr())
    for i, v in enumerate(vals, start=1):
        expect = 2.0 * 64 ** -0.5 * min(i ** -0.5, i * 10 ** -1.5)
        np.testing.assert_allclose(v, expect, rtol=1e-12)
    # warmup is increasing then decaying
    assert vals[0] < vals[8] and vals[14] < max(vals)


def test_generate_proposals_min_size_scaled():
    """reference bbox_util.h FilterBoxes: min_size clamped to >=1, widths
    compared rescaled by im_info[2]. A box of width 8 at im_scale 4 maps to
    original width 2+1=3 and must be DROPPED at min_size 5 even though its
    scaled width 8 would pass the naive check."""
    from paddle_tpu.ops.detection_ops import generate_proposals

    H = W = 4
    A = 1
    scores = np.full((1, A, H, W), 0.5, np.float32)
    deltas = np.zeros((1, A * 4, H, W), np.float32)
    # anchors: one 8x8 box everywhere (decoded ~= anchor at zero deltas)
    anchors = np.tile(np.array([0, 0, 8, 8], np.float32), (H * W * A, 1))
    im_info = np.array([[64.0, 64.0, 4.0]], np.float32)  # scale 4

    _, _, n_keep = generate_proposals(
        scores, deltas, im_info, anchors, min_size=5.0, nms_thresh=0.9)
    assert int(n_keep.numpy()[0]) == 0

    # at im_scale 1 the same boxes (orig extent 8/1+1=9 >= 5) are kept
    im_info1 = np.array([[64.0, 64.0, 1.0]], np.float32)
    _, _, n_keep1 = generate_proposals(
        scores, deltas, im_info1, anchors, min_size=5.0, nms_thresh=0.9)
    assert int(n_keep1.numpy()[0]) > 0


def test_fleet_v1_save_defaults_to_main_program(tmp_path):
    """ADVICE: v1 save_persistables(main_program=None) must fall back to the
    default main program like the reference fleet_base."""
    import paddle_tpu.static as static
    from paddle_tpu.incubate.fleet import fleet

    paddle.enable_static()
    try:
        with paddle.utils.unique_name.guard():
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [4, 2], "float32")
                static.nn.fc(x, 1)
                exe = static.Executor()
                exe.run(startup)
                fleet.init(is_collective=True)
                # documented v1 call pattern: no explicit program
                fleet.save_persistables(exe, str(tmp_path / "persist"))
    finally:
        paddle.disable_static()


def test_resnet_space_to_depth_stem_parity():
    """stem_space_to_depth folds the 7x7/s2 stem into an arithmetically
    identical 4x4/s1 conv on a 2x2-folded input (the MLPerf TPU recipe);
    same parameters, same output."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(3)
    m = resnet18(num_classes=8)
    m.eval()
    x = paddle.to_tensor(
        np.random.RandomState(5).randn(2, 3, 64, 64).astype(np.float32))
    a = m.conv1(x).numpy()
    m.stem_space_to_depth = True
    b = m._stem_s2d(x).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    # gradients flow through the folded path (tape + fold ops)
    m.train()
    out = m(x)
    loss = (out ** 2).mean()
    loss.backward()
    g = m.conv1.weight.grad
    assert g is not None and float(np.abs(g.numpy()).max()) > 0


def test_sdpa_heads_major_parity():
    """_heads_major=True takes [B,H,T,D] inputs/outputs (the flash kernel's
    native layout, used by models.gpt to skip swapaxes copies) and must
    match the standard [B,T,H,D] path bit-for-bit in value and grads."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    B, T, H, D = 2, 16, 4, 8
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)

    def run(hm):
        ts = []
        for a in (q, k, v):
            arr = a.transpose(0, 2, 1, 3) if hm else a
            t = paddle.to_tensor(arr)
            t.stop_gradient = False
            ts.append(t)
        out = F.scaled_dot_product_attention(
            *ts, is_causal=True, _heads_major=hm)
        o = out.numpy().transpose(0, 2, 1, 3) if hm else out.numpy()
        (out ** 2).sum().backward()
        gs = [t.grad.numpy() for t in ts]
        if hm:
            gs = [g.transpose(0, 2, 1, 3) for g in gs]
        return o, gs

    o0, g0 = run(False)
    o1, g1 = run(True)
    np.testing.assert_allclose(o0, o1, rtol=1e-6, atol=1e-6)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_cross_entropy_matches_torch():
    """The fused custom-vjp hard-label CE (no [N,V] log-prob
    materialisation) must match torch in value and gradient, including
    ignore_index rows."""
    import torch
    import torch.nn.functional as tF
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(3)
    x = rng.randn(6, 11).astype(np.float32)
    lab = np.array([1, 0, 10, -100, 4, 7])  # one ignored row

    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    loss = F.cross_entropy(xt, paddle.to_tensor(lab), ignore_index=-100)
    loss.backward()

    tx = torch.tensor(x, requires_grad=True)
    tl = tF.cross_entropy(tx, torch.tensor(lab), ignore_index=-100)
    tl.backward()
    np.testing.assert_allclose(float(loss.numpy()), float(tl.detach()),
                               rtol=1e-5)
    np.testing.assert_allclose(xt.grad.numpy(), tx.grad.numpy(),
                               rtol=1e-4, atol=1e-6)


def test_flash_fallback_warns_once_and_records_path():
    """round-3 VERDICT weak #4: a flash-attention fallback must be loud."""
    import warnings
    import paddle_tpu.nn.functional.attention as attn

    attn._warned_fallback = False
    attn.LAST_PATH = None
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        attn._note_flash(False, RuntimeError("boom"))
        attn._note_flash(False, RuntimeError("boom"))  # only one warning
    assert attn.LAST_PATH == "composed"
    assert sum(issubclass(x.category, RuntimeWarning) for x in w) == 1
    attn._note_flash(True)
    assert attn.LAST_PATH == "flash"
