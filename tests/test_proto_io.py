"""ProgramDesc protobuf wire format (reference framework.proto:202).

Three layers of evidence: deterministic golden bytes, round-trip through
our own parser, and a cross-check with a STOCK protobuf decoder (protoc
compiles the checked-in compat schema at test time; skipped when protoc
is unavailable).
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static.proto_io import (COMPAT_PROTO, parse_program_desc,
                                        serialize_program_desc)


def _tiny_program():
    static = paddle.static
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 3], "float32")
        y = static.data("y", [4, 1], "float32")
        out = static.nn.fc(x, 1)
        loss = ((out - y) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        yield
        paddle.disable_static()


def test_wire_round_trip_preserves_structure():
    main, _, _ = _tiny_program()
    blob = serialize_program_desc(main)
    desc = parse_program_desc(blob)
    blk = desc["blocks"][0]
    live_vars = {v.name: v for v in main.global_block.vars.values()}
    got_vars = {v["name"]: v for v in blk["vars"]}
    assert set(got_vars) == set(live_vars)
    for name, v in live_vars.items():
        assert got_vars[name]["shape"] == [int(d) for d in v.shape], name
        assert got_vars[name]["persistable"] == bool(v.persistable), name
    assert [o["type"] for o in blk["ops"]] == \
        [od.op_type for od in main.ops]
    assert [o["kind"] for o in blk["ops"]] == \
        [od.kind for od in main.ops]
    for o, od in zip(blk["ops"], main.ops):
        assert o["inputs"] == list(od.input_names)
        assert o["outputs"] == list(od.output_names)


def test_golden_bytes_deterministic():
    """Same program → identical bytes (the artifact is content-addressed
    in downstream caches), and the wire prelude is the ProgramDesc
    blocks=1 len-delimited tag followed by BlockDesc idx=0/parent=-1."""
    main, _, _ = _tiny_program()
    b1 = serialize_program_desc(main)
    b2 = serialize_program_desc(main)
    assert b1 == b2
    assert b1[0] == 0x0A  # field 1 (blocks), wire type 2
    # BlockDesc starts: idx=0 (08 00), parent_idx=-1 (10 <10-byte varint>)
    body_start = b1.index(b"\x08\x00\x10")
    assert body_start > 0
    # Version message trailer: field 4 len-delim containing version=0
    assert b1.endswith(b"\x22\x02\x08\x00")


def test_stock_protobuf_decoder_reads_our_bytes(tmp_path):
    """protoc-compile the compat schema and parse our bytes with the
    official protobuf runtime — field-number-level wire compatibility."""
    protoc = shutil.which("protoc")
    if protoc is None:
        pytest.skip("protoc not available")
    (tmp_path / "compat.proto").write_text(COMPAT_PROTO)
    subprocess.run([protoc, f"--python_out={tmp_path}", "compat.proto"],
                   cwd=tmp_path, check=True)
    sys.path.insert(0, str(tmp_path))
    try:
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                              "python")
        import compat_pb2  # noqa: E402
    finally:
        sys.path.pop(0)

    main, _, _ = _tiny_program()
    blob = serialize_program_desc(main)
    pd = compat_pb2.ProgramDesc()
    pd.ParseFromString(blob)
    assert len(pd.blocks) == 1
    blk = pd.blocks[0]
    assert blk.idx == 0 and blk.parent_idx == -1
    live_vars = {v.name for v in main.global_block.vars.values()}
    assert {v.name for v in blk.vars} == live_vars
    # shapes/dtypes survive a stock decode
    by_name = {v.name: v for v in blk.vars}
    for v in main.global_block.vars.values():
        tv = by_name[v.name]
        assert tv.type.type == 7  # LOD_TENSOR
        assert list(tv.type.lod_tensor.tensor.dims) == \
            [int(d) for d in v.shape]
    assert len(blk.ops) == len(main.ops)
    for op, od in zip(blk.ops, main.ops):
        assert op.inputs[0].arguments == list(od.input_names)
        assert op.outputs[0].arguments == list(od.output_names)


def test_ref_op_names_on_the_wire():
    """Ops whose reference name differs are emitted under the REFERENCE
    name (so reference-side tooling reads familiar types) and mapped back
    through the rename table on load."""
    from paddle_tpu.static.proto_io import LOCAL_TO_REF_OP
    main, _, _ = _tiny_program()
    blob = serialize_program_desc(main)
    desc = parse_program_desc(blob)
    for o in desc["blocks"][0]["ops"]:
        if o["type"] in LOCAL_TO_REF_OP:
            assert o["ref_type"] == LOCAL_TO_REF_OP[o["type"]]
        assert o["type"] != ""  # every op mapped back to a local name


def test_checked_in_schema_file_in_sync():
    """paddle_tpu/static/framework_compat.proto is the reviewable copy of
    the codec's schema — must match the COMPAT_PROTO the codec is built
    against."""
    import paddle_tpu.static.proto_io as m
    path = os.path.join(os.path.dirname(m.__file__),
                        "framework_compat.proto")
    assert open(path).read() == COMPAT_PROTO


def test_save_load_retrain_parity_proto_format(tmp_path):
    """save_program(format='proto') → rebuild → load_program → identical
    continued training (the JSON-format contract, now over the proto
    wire)."""
    from paddle_tpu.static.io import load_program, save_program
    static = paddle.static

    main, startup, loss = _tiny_program()
    exe = static.Executor()
    exe.run(startup)
    rs = np.random.RandomState(0)
    xv = rs.randn(4, 3).astype(np.float32)
    yv = rs.randn(4, 1).astype(np.float32)
    for _ in range(3):
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    save_program(main, str(tmp_path / "model"), format="proto")
    # artifact really is proto, not JSON
    raw = (tmp_path / "model.pdmodel").read_bytes()
    assert raw[:1] == b"\x0a"
    expected = exe.run(main, feed={"x": xv, "y": yv},
                       fetch_list=[loss])[0]

    static.global_scope().drop_kids()
    paddle.utils.unique_name.switch()
    main2, startup2, loss2 = _tiny_program()
    load_program(main2, str(tmp_path / "model"))
    resumed = exe.run(main2, feed={"x": xv, "y": yv},
                      fetch_list=[loss2])[0]
    np.testing.assert_allclose(resumed, expected, rtol=1e-6)

    # structural rejection still works through the proto path
    main3 = static.Program()
    startup3 = static.Program()
    with static.program_guard(main3, startup3):
        x = static.data("x", [4, 3], "float32")
        static.nn.fc(x, 2)
    with pytest.raises(ValueError):
        load_program(main3, str(tmp_path / "model"))


def test_packed_repeated_dims_parse():
    """Writers using packed encoding (proto3 default) put all dims in one
    length-delimited payload; the parser must decode them, not coerce to
    0."""
    from paddle_tpu.static.proto_io import _parse_tensor_desc

    def varint(n):
        n &= (1 << 64) - 1
        out = bytearray()
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    # TensorDesc{data_type=5, dims packed [-1, 640, 480]}
    payload = b"".join(varint(d) for d in (-1, 640, 480))
    msg = b"\x08\x05" + b"\x12" + varint(len(payload)) + payload
    dtype, dims = _parse_tensor_desc(msg)
    assert dtype == "float32"
    assert dims == [-1, 640, 480]
