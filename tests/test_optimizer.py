"""Optimizer + LR scheduler + AMP tests (reference analogues:
unittests/test_adam_op.py, test_momentum_op.py, test_imperative_optimizer.py,
test_lr_scheduler.py, test_imperative_auto_mixed_precision.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt

torch = pytest.importorskip("torch")


def _compare_with_torch(make_mine, make_torch, steps=15, rtol=1e-4,
                        atol=1e-5):
    w0 = np.random.randn(5, 3).astype("float32")
    X = np.random.randn(16, 5).astype("float32")
    Y = np.random.randn(16, 3).astype("float32")
    p = paddle.to_tensor(w0.copy(), stop_gradient=False)
    p.trainable = True
    o = make_mine([p])
    tp = torch.tensor(w0.copy(), requires_grad=True)
    to = make_torch([tp])
    for _ in range(steps):
        ((paddle.to_tensor(X) @ p - paddle.to_tensor(Y)) ** 2).mean() \
            .backward()
        o.step()
        o.clear_grad()
        to.zero_grad()
        ((torch.tensor(X) @ tp - torch.tensor(Y)) ** 2).mean().backward()
        to.step()
    np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), rtol=rtol,
                               atol=atol)


def test_sgd():
    _compare_with_torch(lambda ps: opt.SGD(0.05, parameters=ps),
                        lambda ps: torch.optim.SGD(ps, lr=0.05))


def test_momentum_nesterov():
    _compare_with_torch(
        lambda ps: opt.Momentum(0.02, 0.9, parameters=ps,
                                use_nesterov=True),
        lambda ps: torch.optim.SGD(ps, lr=0.02, momentum=0.9,
                                   nesterov=True), rtol=1e-3, atol=1e-4)


def test_adam():
    _compare_with_torch(lambda ps: opt.Adam(0.01, parameters=ps),
                        lambda ps: torch.optim.Adam(ps, lr=0.01))


def test_adamw():
    _compare_with_torch(
        lambda ps: opt.AdamW(0.01, parameters=ps, weight_decay=0.1),
        lambda ps: torch.optim.AdamW(ps, lr=0.01, weight_decay=0.1),
        rtol=1e-3, atol=1e-4)


def test_rmsprop():
    _compare_with_torch(
        lambda ps: opt.RMSProp(0.01, rho=0.9, epsilon=1e-8, parameters=ps),
        lambda ps: torch.optim.RMSprop(ps, lr=0.01, alpha=0.9, eps=1e-8),
        rtol=2e-3, atol=1e-3)


def test_adagrad():
    _compare_with_torch(
        lambda ps: opt.Adagrad(0.05, epsilon=1e-10, parameters=ps),
        lambda ps: torch.optim.Adagrad(ps, lr=0.05), rtol=2e-3, atol=1e-4)


def test_weight_decay_l2():
    w0 = np.ones((3,), np.float32)
    p = paddle.to_tensor(w0.copy(), stop_gradient=False)
    p.trainable = True
    o = opt.SGD(0.1, parameters=[p], weight_decay=0.5)
    (p * 0.0).sum().backward()  # zero data grad; decay only
    o.step()
    np.testing.assert_allclose(p.numpy(), 1 - 0.1 * 0.5, rtol=1e-6)


def test_lamb_trust_ratio_moves():
    p = paddle.to_tensor(np.ones((4, 4), np.float32), stop_gradient=False)
    p.trainable = True
    o = opt.Lamb(0.01, parameters=[p])
    (p ** 2).sum().backward()
    o.step()
    assert not np.allclose(p.numpy(), 1.0)


def test_lr_schedulers():
    s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    seen = []
    for _ in range(5):
        seen.append(round(s(), 5))
        s.step()
    assert seen == [0.1, 0.1, 0.05, 0.05, 0.025]

    s = opt.lr.MultiStepDecay(1.0, milestones=[2, 4], gamma=0.1)
    vals = []
    for _ in range(5):
        vals.append(round(s(), 6))
        s.step()
    assert vals == [1.0, 1.0, 0.1, 0.1, 0.01]

    s = opt.lr.PolynomialDecay(1.0, decay_steps=4, end_lr=0.0, power=1.0)
    vals = []
    for _ in range(5):
        vals.append(round(s(), 4))
        s.step()
    assert vals == [1.0, 0.75, 0.5, 0.25, 0.0]

    s = opt.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
    for loss in [1.0, 1.0, 1.0, 1.0]:
        s.step(loss)
    assert s() == pytest.approx(0.05)


def test_scheduler_drives_optimizer():
    sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
    p = paddle.to_tensor([1.0], stop_gradient=False)
    p.trainable = True
    o = opt.SGD(learning_rate=sched, parameters=[p])
    assert o.get_lr() == pytest.approx(0.1)
    sched.step()
    assert o.get_lr() == pytest.approx(0.01)
    with pytest.raises(RuntimeError):
        o.set_lr(0.5)


def test_optimizer_state_dict_roundtrip():
    p = paddle.to_tensor(np.random.randn(3).astype("f4"),
                         stop_gradient=False)
    p.trainable = True
    o = opt.Adam(0.01, parameters=[p])
    (p ** 2).sum().backward()
    o.step()
    sd = o.state_dict()
    o2 = opt.Adam(0.01, parameters=[p])
    o2.set_state_dict(sd)
    st1 = o._accumulators[id(p)]
    st2 = o2._accumulators[id(p)]
    for k in st1:
        np.testing.assert_allclose(np.asarray(st1[k]), np.asarray(st2[k]))


def test_grad_scaler_skips_on_inf():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    p.trainable = True
    o = opt.SGD(0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0,
                                   decr_every_n_nan_or_inf=1)
    loss = (p * float("inf")).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(o)
    scaler.update()
    np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
    assert scaler.get_init_loss_scaling() == pytest.approx(1.0)


def test_grad_scaler_overflow_counts_skipped_under_zero_grads():
    """REVIEW: an AMP overflow drops the update entirely, so under an
    active zero_grads guard it must land in skipped_steps — counting it as
    zeroed would misreport a dropped step as an applied one."""
    from paddle_tpu.core.anomaly import anomaly_guard

    p = paddle.to_tensor([1.0], stop_gradient=False)
    p.trainable = True
    o = opt.SGD(0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0,
                                   decr_every_n_nan_or_inf=1)
    with anomaly_guard("zero_grads") as g:
        scaled = scaler.scale((p * float("inf")).sum())
        scaled.backward()
        scaler.step(o)
        scaler.update()
    np.testing.assert_allclose(p.numpy(), [1.0])  # update dropped
    assert g.skipped_steps == 1
    assert g.zeroed_steps == 0


def test_auto_cast_bf16():
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = paddle.matmul(a, b)
        assert c.dtype == paddle.bfloat16
        # black-listed op stays f32
        s = paddle.nn.functional.softmax(c.astype("float32"))
        assert s.dtype == paddle.float32
    c2 = paddle.matmul(a, b)
    assert c2.dtype == paddle.float32


def test_train_step_jit_lenet_smoke():
    from paddle_tpu.vision.models import LeNet
    paddle.seed(1)
    model = LeNet()
    optim = opt.Adam(0.002, parameters=model.parameters())

    def loss_fn(m, x, y):
        return F.cross_entropy(m(x), y)

    step = paddle.jit.TrainStep(model, loss_fn, optim)
    x = paddle.randn([16, 1, 28, 28])
    y = paddle.to_tensor(np.random.randint(0, 10, 16))
    l0 = float(step(x, y).numpy())
    for _ in range(10):
        l = float(step(x, y).numpy())
    assert l < l0


def test_master_weights_multi_precision():
    """amp.decorate O2: bf16 params update through fp32 masters (reference:
    fluid/optimizer.py _multi_precision master weights)."""
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 4)
    optim = opt.Adam(learning_rate=1e-4, parameters=lin.parameters())
    lin, optim = paddle.amp.decorate(lin, optim, level="O2",
                                     dtype="bfloat16")
    assert lin.weight.dtype == paddle.bfloat16
    assert optim._multi_precision
    x = paddle.randn([8, 4]).astype("bfloat16")
    # tiny updates that would vanish in bf16 (eps ~ 2^-8 relative) must
    # accumulate in the fp32 master
    for _ in range(100):
        loss = (lin(x) ** 2).sum()
        loss.backward()
        optim.step()
        optim.clear_grad()
    import jax.numpy as jnp
    st = optim._accumulators[id(lin.weight)]
    assert "master" in st and st["master"].dtype == jnp.float32
    # master moved away from the bf16 quantization grid
    assert not np.allclose(np.asarray(st["master"]),
                           lin.weight.numpy(), atol=0)


def test_master_weights_functional_apply_updates():
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 4)
    optim = opt.Adam(learning_rate=1e-3, parameters=lin.parameters())
    optim._multi_precision = True
    import jax.numpy as jnp2
    params = {k: p._value.astype(jnp2.bfloat16)
              for k, p in lin.named_parameters()}
    state = optim.init_opt_state(params)
    for st in state.values():
        assert "master" in st
    grads = {k: jnp2.ones_like(v) for k, v in params.items()}
    new_p, new_s = optim.apply_updates(params, grads, state, 1e-3)
    for k in params:
        assert new_p[k].dtype == jnp2.bfloat16
        assert new_s[k]["master"].dtype == jnp2.float32


def test_master_weights_survive_state_dict_roundtrip():
    """O2 resume: the fp32 master accumulator must round-trip through
    state_dict/set_state_dict (reference: fluid/optimizer.py
    _create_master_weight + load semantics)."""
    import jax.numpy as jnp2
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 4)
    for p in lin.parameters():
        p._value = p._value.astype(jnp2.bfloat16)
    optim = opt.Adam(learning_rate=1e-2, parameters=lin.parameters())
    optim._multi_precision = True
    x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
    lin(x.astype("bfloat16")).sum().backward()
    optim.step()
    optim.clear_grad()
    sd = optim.state_dict()
    assert any(k.endswith("_master") for k in sd)

    lin2 = paddle.nn.Linear(4, 4)
    for p, q in zip(lin2.parameters(), lin.parameters()):
        p._value = q._value
        p.name = q.name  # state-dict keys are accumulator-name based
    optim2 = opt.Adam(learning_rate=1e-2, parameters=lin2.parameters())
    optim2._multi_precision = True
    optim2.set_state_dict(sd)
    for p in lin2.parameters():
        st = optim2._accumulators[id(p)]
        assert "master" in st and st["master"].dtype == jnp2.float32
    # numerics: one more identical step matches the uninterrupted optimizer
    lin(x.astype("bfloat16")).sum().backward()
    lin2(x.astype("bfloat16")).sum().backward()
    optim.step()
    optim2.step()
    for p, q in zip(lin.parameters(), lin2.parameters()):
        np.testing.assert_array_equal(np.asarray(p._value, dtype=np.float32),
                                      np.asarray(q._value, dtype=np.float32))
