"""Multi-replica serving: ReplicaSet router + EngineReplica supervisor
(paddle_tpu/inference/serving/router.py, replica.py).

The load-bearing pins (docs/serving.md "Multi-replica serving and
failover"):

- free-block admission balancing spreads skewed prompt lengths better
  than round-robin (the A/B both policies expose);
- a replica crash/wedge loses ZERO requests: in-flight and queued work
  fails over to survivors in ORIGINAL arrival order (FCFS tickets
  preserved), and requests on untouched replicas stay bitwise-identical
  to an unfaulted run (greedy);
- deadlines keep counting from the ORIGINAL arrival across failover —
  a re-admitted request that blew deadline_s finishes 'timeout';
- a killed replica restarts with capped backoff and rejoins only after
  its warmup probe serves a token end-to-end;
- no replica pool leaks blocks across any mix of completion, failover,
  cancellation and churn (check_integrity per replica).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.inference.serving import (EngineConfig, EngineOverloaded,
                                          ReplicaSet, ReplicaState,
                                          RouterConfig, SamplingParams)
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    return m


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("decode_chunk_size", 2)   # keep requests in flight
    return EngineConfig(**kw)


def _router(model, n=2, faults=None, ecfg=None, **rkw):
    rkw.setdefault("backoff_base", 0.01)
    rkw.setdefault("backoff_max", 0.05)
    rkw.setdefault("backoff_jitter", 0.0)
    return ReplicaSet.from_model(
        model, RouterConfig(num_replicas=n, **rkw),
        engine_config=ecfg or _ecfg(),
        faults=faults or ServingFaultInjector(""))


def _prompts(n, seed=7, lo=3, hi=8):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, int(rng.randint(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _assert_no_leaks(rs):
    for idx, audit in rs.check_integrity().items():
        assert audit is not None, f"replica {idx} has no live engine"
        assert audit["leaked"] == 0, (idx, audit)


# ---------------------------------------------------------- balancing
def test_free_block_balancing_beats_round_robin(model):
    # L,S,L,S is adversarial to round-robin (both longs land on the
    # same replica) while free-block scoring absorbs each long before
    # routing the next; no stepping, so demand is purely admission-time
    long_p = np.arange(1, 15, dtype=np.int32)        # 14 tokens
    short_p = np.arange(1, 4, dtype=np.int32)        # 3 tokens
    order = [long_p, short_p, long_p, short_p]

    def imbalance(balance):
        rs = ReplicaSet.from_model(
            model, RouterConfig(num_replicas=2, balance=balance),
            engine_config=_ecfg(num_blocks=32))
        for p in order:
            rs.add_request(p, SamplingParams(max_tokens=4))
        d = [r.load_info()["block_demand"] for r in rs.replicas]
        rs.run(max_steps=500)
        _assert_no_leaks(rs)
        return abs(d[0] - d[1])

    fb, rr = imbalance("free_blocks"), imbalance("round_robin")
    assert fb < rr, (fb, rr)


def test_round_robin_rotates(model):
    rs = ReplicaSet.from_model(
        model, RouterConfig(num_replicas=3, balance="round_robin"),
        engine_config=_ecfg())
    homes = []
    for p in _prompts(6):
        rid = rs.add_request(p, SamplingParams(max_tokens=2))
        homes.append(rs.get_request(rid).replica)
    assert homes == [0, 1, 2, 0, 1, 2]
    rs.run(max_steps=500)
    _assert_no_leaks(rs)


# ----------------------------------------------------------- failover
def test_failover_zero_lost_and_bitwise_untouched(model):
    prompts = _prompts(6)
    sp = lambda: SamplingParams(max_tokens=8)  # noqa: E731

    faults = ServingFaultInjector("kill_replica@3:1")
    rs = _router(model, n=3, faults=faults)
    rids = [rs.add_request(p, sp()) for p in prompts]
    homes = {r: rs.get_request(r).replica for r in rids}
    rs.run(max_steps=3000)
    assert faults.fired_log, "kill fault never fired"

    st = rs.router_stats()
    assert st["unfinished"] == 0                     # zero lost
    assert st["requeues"] >= 1                       # failover happened
    assert all(rs.get_request(r).finish_reason == "length" for r in rids)
    _assert_no_leaks(rs)

    ref = _router(model, n=3)
    ref_rids = [ref.add_request(p, sp()) for p in prompts]
    ref.run(max_steps=1500)
    untouched = 0
    for r, rr in zip(rids, ref_rids):
        rec = rs.get_request(r)
        if rec.requeues == 0 and homes[r] != 1:
            untouched += 1
            assert rec.tokens == ref.get_request(rr).tokens
    assert untouched > 0
    # greedy decode is bitwise across failover too (re-prefill +
    # fold_in(seed, progress) sampling keys): ALL requests must match
    for r, rr in zip(rids, ref_rids):
        assert rs.get_request(r).tokens == ref.get_request(rr).tokens


def test_fcfs_arrival_order_preserved_across_requeue(model):
    # all six requests land on replica 1 of 2 after filling replica 0's
    # score down is fiddly; instead kill r1 and inspect the SURVIVOR's
    # scheduler: readmitted requests must carry their ORIGINAL tickets
    # and sit in arrival order
    faults = ServingFaultInjector("kill_replica@1:1")
    rs = _router(model, n=2, faults=faults)
    rids = [rs.add_request(p, SamplingParams(max_tokens=6))
            for p in _prompts(6)]
    tickets = {r: rs.get_request(r).arrival for r in rids}
    rs.step()                                        # fires the kill
    assert rs.states()[1] in (ReplicaState.DOWN, ReplicaState.FAILED)
    # every request now lives on replica 0 with its original ticket
    for r in rids:
        rec = rs.get_request(r)
        if rec.finished:
            continue
        assert rec.replica == 0
        assert rec.arrival == tickets[r]
    sched = rs.replicas[0].engine.scheduler
    waiting = [q.arrival for q in sched.waiting]
    assert waiting == sorted(waiting), \
        "requeue must keep the waiting queue in original arrival order"
    rs.run(max_steps=3000)
    assert rs.router_stats()["unfinished"] == 0
    _assert_no_leaks(rs)


def test_deadline_counts_from_original_arrival_across_failover(model):
    # satellite regression: a request whose replica dies does NOT get a
    # fresh deadline on re-admission — deadline_s is measured from the
    # ORIGINAL arrival_time, so one that blew its budget during the
    # failover finishes 'timeout'
    faults = ServingFaultInjector("kill_replica@1:1")
    rs = _router(model, n=2, faults=faults)
    keep = rs.add_request(_prompts(1)[0], SamplingParams(max_tokens=4))
    doomed = rs.add_request(
        _prompts(2)[1], SamplingParams(max_tokens=16, deadline_s=0.05))
    assert rs.get_request(doomed).replica == 1
    t_orig = rs.get_request(doomed).arrival_time
    rs.step()                                        # kill + readmit
    assert rs.get_request(doomed).requeues == 1
    assert rs.get_request(doomed).replica == 0
    # the engine-side clone must carry the ORIGINAL arrival stamp
    eng_req = rs.replicas[0].engine.get_request(doomed)
    assert eng_req.arrival_time == t_orig
    time.sleep(0.06)                                 # blow the budget
    rs.run(max_steps=3000)
    assert rs.get_request(doomed).finish_reason == "timeout"
    assert rs.get_request(keep).finish_reason == "length"
    _assert_no_leaks(rs)


def test_wedge_failover_via_heartbeat(model):
    faults = ServingFaultInjector("wedge_replica@2:0")
    rs = _router(model, n=2, faults=faults, heartbeat_timeout_s=0.01)
    rids = [rs.add_request(p, SamplingParams(max_tokens=6))
            for p in _prompts(6)]
    steps = 0
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps < 3000
        time.sleep(0.002)    # let the wedged replica's silence accrue
    st = rs.router_stats()
    assert st["unfinished"] == 0
    assert st["requeues"] >= 1
    assert any("wedge" in reason
               for r in rs.replicas for _, reason in r.history)
    assert all(rs.get_request(r).finished for r in rids)


# ----------------------------------------------------- restart/rejoin
def test_killed_replica_rejoins_after_warmup_probe(model):
    faults = ServingFaultInjector("kill_replica@2:1")
    rs = _router(model, n=2, faults=faults)
    rids = [rs.add_request(p, SamplingParams(max_tokens=8))
            for p in _prompts(6)]
    rs.run(max_steps=3000)
    rep = rs.replicas[1]
    assert rep.state == ReplicaState.UP
    assert rep.restarts == 1
    assert rep.probe_tokens >= 1          # the probe actually served
    assert len(rs.recovery_times) == 1
    assert rs.router_stats()["unfinished"] == 0
    # the rejoined replica serves real traffic: drain the other one so
    # routing has a single destination
    rs.drain(0)
    canary = rs.add_request(_prompts(1)[0], SamplingParams(max_tokens=2))
    assert rs.get_request(canary).replica == 1
    rs.run(max_steps=1000)
    assert rs.get_request(canary).finish_reason == "length"
    rs.undrain(0)
    _assert_no_leaks(rs)
    assert all(rs.get_request(r).finished for r in rids)


def test_probe_failure_counts_against_restart_budget(model):
    # an engine factory whose second incarnation cannot serve sends the
    # replica through quarantine → restart → failed probe → FAILED once
    # the budget is spent; the orphans terminalize 'error', never lost
    from paddle_tpu.inference.serving.engine import LLMEngine

    calls = []

    def factory(index, incarnation):
        calls.append(incarnation)
        if incarnation > 0:
            raise RuntimeError("fresh engine refuses to boot")
        return LLMEngine.from_model(model, _ecfg())

    faults = ServingFaultInjector("kill_replica@2:0")
    rs = ReplicaSet(factory,
                    RouterConfig(num_replicas=1, max_restarts=2,
                                 backoff_base=0.005, backoff_max=0.01,
                                 backoff_jitter=0.0),
                    faults=faults)
    rids = [rs.add_request(p, SamplingParams(max_tokens=6))
            for p in _prompts(3)]
    steps = 0
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps < 3000
        time.sleep(0.002)
    assert rs.states()[0] == ReplicaState.FAILED
    for r in rids:
        assert rs.get_request(r).finish_reason == "error"
    assert len(calls) >= 2                # the restart path did run


# ------------------------------------------------------- backpressure
def test_router_reject_carries_retry_after_hint(model):
    rs = _router(model, n=1, max_waiting=1, admission_policy="reject",
                 ecfg=_ecfg(max_num_seqs=1))
    rs.add_request(_prompts(1)[0], SamplingParams(max_tokens=4))
    rs.step()                            # admit it to running
    rs.add_request(_prompts(2)[1], SamplingParams(max_tokens=4))
    with pytest.raises(EngineOverloaded) as ei:
        rs.add_request(_prompts(3)[2], SamplingParams(max_tokens=4))
    assert ei.value.retry_after_s is not None
    assert ei.value.retry_after_s > 0
    assert "retry after" in str(ei.value)
    rs.run(max_steps=1000)
    _assert_no_leaks(rs)


def test_router_shed_oldest_spans_replicas(model):
    rs = _router(model, n=2, max_waiting=2,
                 admission_policy="shed_oldest",
                 ecfg=_ecfg(max_num_seqs=1))
    prompts = _prompts(6)
    rids = [rs.add_request(p, SamplingParams(max_tokens=8))
            for p in prompts[:2]]
    rs.step()                           # both running, waiting empty
    rids += [rs.add_request(p, SamplingParams(max_tokens=8))
             for p in prompts[2:4]]     # one waiting per replica
    victim = min((r for r in rids[2:]),
                 key=lambda r: rs.get_request(r).arrival)
    extra = rs.add_request(prompts[4], SamplingParams(max_tokens=4))
    rs.run(max_steps=1000)
    assert rs.get_request(victim).finish_reason == "shed"
    assert rs.get_request(extra).finish_reason == "length"
    _assert_no_leaks(rs)


def test_no_up_replica_rejects_with_hint(model):
    rs = _router(model, n=1)
    rs.drain(0)
    with pytest.raises(EngineOverloaded) as ei:
        rs.add_request(_prompts(1)[0], SamplingParams(max_tokens=2))
    assert ei.value.retry_after_s is not None


# ------------------------------------------------------------- churn
def test_churn_zero_leak_with_failover(model):
    # 200-request churn (small generations, staggered arrivals, random
    # cancels) across 3 replicas with one kill mid-stream: everything
    # terminal, zero leaks on every replica
    rng = np.random.RandomState(3)
    n = 200
    specs = [(rng.randint(0, VOCAB, int(rng.randint(3, 8)))
              .astype(np.int32), int(rng.randint(2, 5)))
             for _ in range(n)]
    faults = ServingFaultInjector("kill_replica@8:2")
    rs = _router(model, n=3, faults=faults,
                 ecfg=_ecfg(decode_chunk_size=4, num_blocks=24))
    pending = list(specs)
    rids, cancelled = [], 0
    steps = 0
    while pending or rs.has_unfinished():
        for _ in range(min(2, len(pending))):
            p, mt = pending.pop(0)
            rids.append(rs.add_request(p, SamplingParams(max_tokens=mt)))
        rs.step()
        steps += 1
        assert steps < 6000
        if steps % 7 == 0 and rids:
            live = [r for r in rids
                    if not rs.get_request(r).finished]
            if live:
                if rs.cancel(live[int(rng.randint(len(live)))]):
                    cancelled += 1
        if not any(r.has_unfinished() for r in rs.replicas) \
                and rs.has_unfinished():
            time.sleep(0.002)
    assert len(rids) == n
    assert faults.fired_log, "kill fault never fired"
    st = rs.router_stats()
    assert st["unfinished"] == 0
    assert st["requeues"] >= 1
    assert cancelled > 0
    _assert_no_leaks(rs)


# -------------------------------------------------- chaos acceptance
@pytest.mark.chaos
def test_replica_chaos_acceptance(model):
    # the PR's acceptance gate, in-process: 3 replicas, kill_replica
    # mid-traffic + engine-level poison — every request terminal,
    # untouched-replica requests bitwise vs unfaulted, zero leaks per
    # replica, killed replica rejoins and serves a canary in-run
    import tools.chaos_serve as cs
    report = cs.run_chaos_replicas(seed=0, n_requests=12, replicas=3)
    assert report["requeues"] >= 1
    assert report["canaries_served"] >= 1
    assert report["untouched_survivors"] > 0
    for audit in report["integrity"].values():
        assert audit["leaked"] == 0


# ------------------------------------------------------------- obs
def test_router_metrics_families(model):
    from paddle_tpu import obs
    faults = ServingFaultInjector("kill_replica@2:0")
    rs = _router(model, n=2, faults=faults)
    for p in _prompts(4):
        rs.add_request(p, SamplingParams(max_tokens=6))
    rs.run(max_steps=3000)
    fams = {f["name"]: f for f in obs.snapshot()["metrics"]}
    for name in ("serving_replica_up", "serving_failovers_total",
                 "serving_requeued_total", "serving_router_ttft_seconds",
                 "serving_failover_recovery_seconds"):
        assert name in fams, name
    ups = [s["value"] for s in fams["serving_replica_up"]["series"]
           if s["labels"]["router"] == rs.label]
    assert len(ups) == 2 and all(v == 1 for v in ups)
    fo = [s for s in fams["serving_failovers_total"]["series"]
          if s["labels"]["router"] == rs.label]
    assert sum(s["value"] for s in fo) >= 1
    assert any(s["labels"]["reason"] == "crash" for s in fo)
    req = [s for s in fams["serving_requeued_total"]["series"]
           if s["labels"]["router"] == rs.label]
    assert sum(s["value"] for s in req) >= 1
    rec = [s for s in fams["serving_failover_recovery_seconds"]["series"]
           if s["labels"]["router"] == rs.label]
    assert sum(s["count"] for s in rec) == 1
