"""Dtype-promotion guards (VERDICT r1 weak #7): jax_enable_x64 is on
globally (paddle's int64 default), which makes stray Python floats able to
promote computations to float64 — a dtype TPUs do not execute natively.
These tests pin the common API surfaces to f32/bf16.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_scalar_arith_stays_f32():
    x = paddle.ones([4], dtype="float32")
    for expr in (x * 2.0, x + 0.5, 2.0 * x, x / 3.0, x - 1.0,
                 x ** 2.0, x * np.pi):
        assert expr.dtype == paddle.float32, expr.dtype


def test_functional_surface_stays_f32():
    paddle.seed(0)
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    assert F.softmax(x).dtype == paddle.float32
    assert F.gelu(x).dtype == paddle.float32
    assert F.layer_norm(x, [8]).dtype == paddle.float32
    assert F.dropout(x, 0.5, training=True).dtype == paddle.float32
    lab = paddle.to_tensor(np.random.randint(0, 8, (4,)))
    assert F.cross_entropy(x, lab).dtype == paddle.float32
    lin = paddle.nn.Linear(8, 4)
    assert lin(x).dtype == paddle.float32


def test_layer_forward_bf16_stays_bf16():
    import jax.numpy as jnp
    paddle.seed(0)
    lin = paddle.nn.Linear(8, 4)
    lin._value = None  # unused guard; params cast below
    for p in lin.parameters():
        p._value = p._value.astype(jnp.bfloat16)
    x = paddle.to_tensor(np.random.randn(2, 8).astype("float32")).astype(
        "bfloat16")
    out = lin(x)
    assert out.dtype == paddle.bfloat16, out.dtype
    # scalar epilogue must not promote past f32
    assert (out * 2.0).dtype == paddle.bfloat16


def test_optimizer_keeps_param_dtype():
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(1e-3, parameters=lin.parameters())
    (lin(paddle.ones([2, 4])) ** 2).mean().backward()
    opt.step()
    for p in lin.parameters():
        assert p.dtype == paddle.float32, (p.name, p.dtype)


def test_train_step_no_f64_in_module():
    """The compiled train step must contain no f64 ops (TPU executes f64
    via slow emulation; a stray promotion would silently tank perf)."""
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 8), paddle.nn.GELU(),
                                 paddle.nn.Linear(8, 2))
    optim = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda m, x, y: paddle.nn.functional.cross_entropy(m(x), y),
        optim)
    import jax
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.random.randint(0, 2, (4, 1)))
    # lower without executing and scan the StableHLO text
    params, frozen = step._split_params()
    buffers = {}
    opt_state = step.optimizer.init_opt_state(params)
    import jax.numpy as jnp
    lowered = step._step.lower(
        params, frozen, buffers, opt_state, jnp.asarray(1e-3, jnp.float32),
        jax.random.PRNGKey(0), jnp.asarray(1, jnp.uint32),
        x._value, y._value)
    txt = lowered.as_text()
    # scalar f64 CONSTANTS (weak-typed python literals immediately
    # converted) are harmless; f64 ARRAYS mean a real promotion leak
    import re
    leaks = re.findall(r"tensor<\d+[x\d]*xf64>", txt)
    assert not leaks, f"float64 arrays leaked into the train step: {leaks}"


def test_numpy_scalars_are_not_weak():
    """np.float64 subclasses float but is strong-typed — it must wrap to
    the default dtype, not poison the result with f64."""
    x = paddle.ones([4], dtype="float32")
    s = np.float64(2.0)   # e.g. np.mean(losses)
    assert (x * s).dtype == paddle.float32
    assert (s * x).dtype == paddle.float32
    b = x.astype("bfloat16")
    assert (b * np.float64(2.0)).dtype != paddle.float64


def test_bool_and_int_division_promotion():
    mask = paddle.to_tensor(np.array([True, False]))
    assert (mask * 0.5).dtype == paddle.float32  # not f64
    ints = paddle.ones([3], dtype="int64")
    assert (ints / 2).dtype == paddle.float32
    assert (ints / 2.0).dtype == paddle.float32
    assert paddle.divide(ints, paddle.to_tensor(
        np.array([2, 2, 2]))).dtype == paddle.float32
