"""Disaggregated prefill/decode serving with live KV-block migration
(paddle_tpu/inference/serving/migration.py + router roles/tiering).

The load-bearing pins (docs/serving.md "Disaggregated serving and
block migration"):

- greedy output after a migration is BITWISE-identical to the same
  request served unmigrated — pinned for handoff (prefill tier ->
  decode tier), rebalance() and drain(recompute=False);
- zero leaked blocks and a clean check_integrity on BOTH ends of every
  migration, including prefix-shared blocks under refcount (shared
  blocks are copied, never stolen — the source trie keeps its entry);
- drain(recompute=False) evacuates live requests with ZERO
  re-prefilled tokens (prefill counters frozen across the drain);
- migrate_out/migrate_in trace events pair up (same arrival ticket,
  matching src/dst replicas) and the reqtrace causality checker
  machine-verifies the pairing;
- a source replica killed INSIDE the migration commit window loses
  nothing: the destination rolls back, the victim re-prefills from the
  router's token log, survivors stay bitwise (chaos gate, 3 seeds).
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.inference.serving import (EngineConfig, ReplicaSet,
                                          RouterConfig, SamplingParams)
from paddle_tpu.obs.reqtrace import ReqTraceRing
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=48)
    m = GPT(cfg)
    m.eval()
    return m


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("decode_chunk_size", 2)   # keep requests in flight
    return EngineConfig(**kw)


def _router(model, n=2, roles=None, ecfg=None, **rkw):
    rkw.setdefault("backoff_base", 0.01)
    rkw.setdefault("backoff_max", 0.05)
    rkw.setdefault("backoff_jitter", 0.0)
    return ReplicaSet.from_model(
        model, RouterConfig(num_replicas=n, roles=roles, **rkw),
        engine_config=ecfg or _ecfg(),
        faults=ServingFaultInjector(""))


def _prompts(n, seed=7, lo=6, hi=14):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, VOCAB, int(rng.randint(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _run(rs, prompts, max_tokens=12, max_steps=400):
    rids = [rs.add_request(p, SamplingParams(max_tokens=max_tokens))
            for p in prompts]
    steps = 0
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps <= max_steps, "router failed to drain"
    return rids


def _tokens(rs, rids):
    return [list(rs.get_request(r).tokens) for r in rids]


def _assert_clean(rs):
    for idx, audit in rs.check_integrity().items():
        assert audit is not None, f"replica {idx} has no live engine"
        for k, v in audit.items():
            if isinstance(v, int):
                assert v == 0, (idx, k, audit)


# ------------------------------------------------------- role plumbing
def test_roles_validation(model):
    with pytest.raises(ValueError):        # wrong length
        _router(model, n=2, roles=("prefill",))
    with pytest.raises(ValueError):        # unknown role
        _router(model, n=2, roles=("prefill", "turbo"))
    with pytest.raises(ValueError):        # nobody to decode
        _router(model, n=2, roles=("prefill", "prefill"))
    rs = _router(model, n=2, roles=("prefill", "decode"))
    assert [r.role for r in rs.replicas] == ["prefill", "decode"]
    rs2 = _router(model, n=2)              # default: all mixed
    assert [r.role for r in rs2.replicas] == ["mixed", "mixed"]


# ------------------------------------------------ handoff: bitwise pin
def test_handoff_bitwise_and_integrity(model):
    prompts = _prompts(5)
    base = _tokens(*((rs := _router(model, n=2)),
                     _run(rs, prompts)))
    tiered = _router(model, n=2, roles=("prefill", "decode"))
    rids = _run(tiered, prompts)
    # every request was handed off exactly once and finished on the
    # decode tier
    assert tiered.migrator.stats()["migrations"] == len(prompts)
    assert all(tiered.get_request(r).replica == 1 for r in rids)
    # greedy output is bitwise-identical to the unmigrated fleet
    assert _tokens(tiered, rids) == base
    _assert_clean(tiered)


def test_handoff_preserves_fcfs_arrival_ticket(model):
    tiered = _router(model, n=2, roles=("prefill", "decode"))
    prompts = _prompts(4, seed=11)
    rids = _run(tiered, prompts)
    assert tiered.migrator.stats()["migrations"] == len(prompts)
    # the router record's arrival stamp is the FCFS ticket; migration
    # must carry it unchanged (resume, not re-enqueue)
    arrivals = [tiered.get_request(r).arrival for r in rids]
    assert arrivals == sorted(arrivals)


# ---------------------------- shared prefix: copied, never stolen
def test_migration_copies_shared_prefix_blocks(model):
    tpl = np.arange(1, 17, dtype=np.int32)          # 4 full blocks
    leader = np.concatenate([tpl, np.array([40, 41, 42], np.int32)])
    follower = np.concatenate([tpl, np.array([50, 51], np.int32)])

    # reference: same two prompts, tiered, prefix cache OFF
    ref = _router(model, n=2, roles=("prefill", "decode"))
    ref_toks = _tokens(ref, _run(ref, [leader, follower]))

    ecfg = _ecfg(enable_prefix_cache=True)
    rs = _router(model, n=2, roles=("prefill", "decode"), ecfg=ecfg)
    r0 = rs.add_request(leader, SamplingParams(max_tokens=12))
    steps = 0
    # run until the leader has been migrated off the prefill tier —
    # its template blocks now live ONLY via the source trie's entry
    while rs.migrator.stats()["migrations"] < 1:
        rs.step()
        steps += 1
        assert steps <= 50, "leader never handed off"
    r1 = rs.add_request(follower, SamplingParams(max_tokens=12))
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps <= 400
    # the follower HIT the trie entry the migrated leader left behind:
    # migration copied the shared blocks, it did not steal them
    src = rs.replicas[0].engine.cache.prefix_stats()
    assert src["hits"] >= 1, src
    # the destination registered the migrated prefixes into its own
    # trie (entries survive the requests finishing)
    dst = rs.replicas[1].engine.cache.prefix_stats()
    assert dst["evictable_blocks"] > 0, dst
    assert rs.migrator.stats()["migrations"] == 2
    assert _tokens(rs, [r0, r1]) == ref_toks
    _assert_clean(rs)


# ------------------------------------------------- rebalance: bitwise
def test_rebalance_moves_cold_requests_bitwise(model):
    prompts = _prompts(4, seed=3, lo=10, hi=14)
    base = _tokens(*((rs := _router(model, n=2)),
                     _run(rs, prompts, max_tokens=16)))
    # roles ("mixed","decode") funnel every admission onto replica 0,
    # manufacturing the occupancy skew rebalance exists to fix
    skew = _router(model, n=2, roles=("mixed", "decode"))
    rids = [skew.add_request(p, SamplingParams(max_tokens=16))
            for p in prompts]
    for _ in range(3):
        skew.step()
    occ0 = 1 - (skew.replicas[0].load_info()["free_blocks"]
                / skew.replicas[0].engine.cache.num_blocks)
    assert occ0 > 0.3                       # the skew is real
    moved = skew.rebalance(watermark=0.3)
    assert moved >= 1
    assert skew.migrator.stats()["migrations"] == moved
    steps = 0
    while skew.has_unfinished():
        skew.step()
        steps += 1
        assert steps <= 400
    assert _tokens(skew, rids) == base
    _assert_clean(skew)


def test_rebalance_noop_below_watermark(model):
    rs = _router(model, n=2)
    _run(rs, _prompts(3))
    assert rs.rebalance(watermark=0.95) == 0
    with pytest.raises(ValueError):
        rs.rebalance(watermark=0.0)


# --------------------------------------- drain without recomputation
def test_drain_evacuates_with_zero_reprefill(model):
    prompts = _prompts(3, seed=5, lo=8, hi=12)
    base = _tokens(*((rs := _router(model, n=2)),
                     _run(rs, prompts, max_tokens=16)))
    rs = _router(model, n=2)
    rids = [rs.add_request(p, SamplingParams(max_tokens=16))
            for p in prompts]
    for _ in range(2):                      # all rows prefilled, mid-decode
        rs.step()

    def prefill_spend():
        return sum(r.engine.stats.as_dict()["prefill_tokens"]
                   + r.engine.stats.prefill_chunks()
                   for r in rs.replicas if r.engine is not None)

    spent = prefill_spend()
    rs.drain(0, recompute=False)
    steps = 0
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps <= 400
    # live requests moved via KV-block migration: not one prefill token
    # (dense or chunked) was recomputed anywhere in the fleet
    assert prefill_spend() == spent
    assert rs.migrator.stats()["migrations"] >= 1
    assert str(rs.states()[0]) == "drained"
    assert _tokens(rs, rids) == base
    _assert_clean(rs)


# ------------------------------------------------ reqtrace invariants
def test_migrate_trace_events_pair_and_check_clean(model):
    obs.reqtrace.enable()
    rs = _router(model, n=2, roles=("prefill", "decode"))
    _run(rs, _prompts(3, seed=9))
    ids = sorted(obs.reqtrace.traces(prefix=f"tr-{rs.label}-"))
    dump = obs.reqtrace.dump_payload("test", trace_ids=ids,
                                     complete=True)
    assert obs.reqtrace.check_causality(dump) == []
    by_trace = {}
    for e in dump["events"]:
        by_trace.setdefault(e["trace_id"], []).append(e)
    assert len(by_trace) == 3
    for tid, evts in by_trace.items():
        outs = [e for e in evts if e["kind"] == "migrate_out"]
        ins = [e for e in evts if e["kind"] == "migrate_in"]
        assert len(outs) == 1 and len(ins) == 1, tid
        o, i = outs[0]["attrs"], ins[0]["attrs"]
        assert o["to_replica"] == i["replica"]
        assert i["from_replica"] == o["replica"]
        assert o["arrival"] == i["arrival"]     # FCFS ticket constant
        assert o["blocks"] == i["blocks"] and o["bytes"] == i["bytes"]
        assert i["prefilled"] is True


def test_checker_flags_migrate_violations():
    # migrate_in with no preceding migrate_out
    r = ReqTraceRing()
    r.record("engine_admit", "tM0", engine="e-0", arrival=0)
    r.record("scheduled", "tM0", arrival=0)
    r.record("prefill", "tM0")
    r.record("migrate_in", "tM0", replica=1, from_replica=0,
             engine="e-1", arrival=0, prefilled=True)
    r.record("finish", "tM0", reason="stop")
    bad = {"version": 1, "complete": True,
           "events": [e.as_dict() for e in r.events()]}
    assert any("migrate_out" in v for v in
               obs.reqtrace.check_causality(bad))

    # migrate_in naming the wrong source replica
    r.clear()
    r.record("engine_admit", "tM1", engine="e-0", arrival=0)
    r.record("scheduled", "tM1", arrival=0)
    r.record("prefill", "tM1")
    r.record("migrate_out", "tM1", replica=0, to_replica=1, arrival=0)
    r.record("migrate_in", "tM1", replica=1, from_replica=2,
             engine="e-1", arrival=0, prefilled=True)
    r.record("finish", "tM1", reason="stop")
    bad = {"version": 1, "complete": True,
           "events": [e.as_dict() for e in r.events()]}
    assert any("source replica" in v for v in
               obs.reqtrace.check_causality(bad))

    # token emission between migrate_out and migrate_in: the request
    # has no home engine in that window, nothing may decode it
    r.clear()
    r.record("engine_admit", "tM2", engine="e-0", arrival=0)
    r.record("scheduled", "tM2", arrival=0)
    r.record("prefill", "tM2")
    r.record("migrate_out", "tM2", replica=0, to_replica=1, arrival=0)
    r.record("first_token", "tM2")
    r.record("migrate_in", "tM2", replica=1, from_replica=0,
             engine="e-1", arrival=0, prefilled=True)
    r.record("finish", "tM2", reason="stop")
    bad = {"version": 1, "complete": True,
           "events": [e.as_dict() for e in r.events()]}
    assert any("prefill" in v for v in
               obs.reqtrace.check_causality(bad))


# ------------------------------------------- chaos: kill mid-migration
def _run_chaos_disagg(**kw):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from chaos_serve import run_chaos_disagg
    finally:
        sys.path.pop(0)
    return run_chaos_disagg(**kw)


@pytest.mark.chaos
def test_chaos_kill_mid_migration(model):
    # the harness itself asserts the gates (zero lost, zero leaks on
    # both ends, bitwise survivors, witness clean); here we pin that
    # the fault actually landed in the commit window and rolled back
    rep = _run_chaos_disagg(seed=0, n_requests=10)
    assert rep["migrations"]["rolled_back"] >= 1
    assert rep["migrations"]["migrations"] >= 1
    assert rep["survivors"] == 10
    assert not rep["lockgraph"]["cycles"]
    assert not rep["lockgraph"]["unpredicted_edges"]


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
def test_chaos_kill_mid_migration_more_seeds(seed):
    rep = _run_chaos_disagg(seed=seed, n_requests=10)
    assert rep["migrations"]["rolled_back"] >= 1
    assert rep["survivors"] == 10
