"""Tests for the op-corpus tail: fft, array/meta, random, sequence,
control flow, vision/detection, fused, quant, optimizer ops, extras.

Oracles: numpy/scipy-free numpy + torch where available (the reference
verifies the same families through OpTest with CPU-kernel oracles).
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import (array_ops, control_flow, extra_ops, fused_ops,
                            metrics_ops, quant_ops, random_ops,
                            sequence_ops, vision_ops, optimizer_ops)

rng = np.random.RandomState(3)


def r(*shape, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


# ------------------------------------------------------------------- fft
def test_fft_matches_numpy():
    x = r(4, 8)
    np.testing.assert_allclose(paddle.fft.fft(paddle.to_tensor(x)).numpy(),
                               np.fft.fft(x), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(paddle.fft.rfft(paddle.to_tensor(x)).numpy(),
                               np.fft.rfft(x), rtol=1e-4, atol=1e-5)
    c = (r(4, 8) + 1j * r(4, 8)).astype(np.complex64)
    np.testing.assert_allclose(paddle.fft.ifft(paddle.to_tensor(c)).numpy(),
                               np.fft.ifft(c), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.fft.fft2(paddle.to_tensor(x)).numpy(),
        np.fft.fft2(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        paddle.fft.fftshift(paddle.to_tensor(x)).numpy(),
        np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.fft.fftfreq(8, 0.5).numpy(),
                               np.fft.fftfreq(8, 0.5), rtol=1e-6)


def test_fft_grad():
    x = paddle.to_tensor(r(8), stop_gradient=False)
    out = paddle.fft.rfft(x)
    (out.numpy() is not None)
    loss = (paddle.as_real(out) ** 2).sum()
    loss.backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


# ------------------------------------------------------------- array ops
def test_shape_size_rank_unbind_meshgrid():
    x = paddle.ones([2, 3, 4])
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3, 4])
    assert int(paddle.numel(x).numpy()) == 24
    assert int(paddle.rank(x).numpy()) == 3
    parts = paddle.unbind(x, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 4]
    a, b = paddle.meshgrid(paddle.to_tensor([1., 2.]),
                           paddle.to_tensor([3., 4., 5.]))
    assert a.shape == [2, 3] and b.shape == [2, 3]
    np.testing.assert_array_equal(a.numpy(), [[1, 1, 1], [2, 2, 2]])


def test_unique_and_consecutive():
    x = paddle.to_tensor(np.array([2, 1, 2, 3, 1]))
    np.testing.assert_array_equal(paddle.unique(x).numpy(), [1, 2, 3])
    vals, counts = paddle.unique(x, return_counts=True)
    np.testing.assert_array_equal(counts.numpy(), [2, 2, 1])
    y = paddle.to_tensor(np.array([1, 1, 2, 2, 3, 1]))
    np.testing.assert_array_equal(
        array_ops.unique_consecutive(y).numpy(), [1, 2, 3, 1])


def test_tensor_array_roundtrip():
    arr = array_ops.create_array()
    array_ops.array_write(paddle.ones([2]), 0, arr)
    array_ops.array_write(paddle.zeros([2]), 1, arr)
    assert int(array_ops.array_length(arr).numpy()) == 2
    np.testing.assert_array_equal(array_ops.array_read(arr, 0).numpy(),
                                  [1, 1])


def test_broadcast_tensors_and_crop():
    outs = paddle.broadcast_tensors([paddle.ones([1, 3]),
                                     paddle.zeros([4, 1])])
    assert outs[0].shape == [4, 3] and outs[1].shape == [4, 3]
    x = np.arange(24, dtype="float32").reshape(4, 6)
    out = paddle.crop(paddle.to_tensor(x), shape=[2, 3], offsets=[1, 2])
    np.testing.assert_array_equal(out.numpy(), x[1:3, 2:5])


# ------------------------------------------------------------- random ops
def test_random_ops_distributions():
    paddle.seed(7)
    p = paddle.full([2000], 0.3)
    draws = random_ops.bernoulli(p)
    assert 0.2 < float(draws.numpy().mean()) < 0.4
    probs = paddle.to_tensor(np.array([[0.8, 0.1, 0.1]], np.float32))
    m = random_ops.multinomial(probs, 200, replacement=True)
    assert (np.bincount(m.numpy()[0], minlength=3)[0] > 100)
    m2 = random_ops.multinomial(paddle.ones([1, 5]), 5, replacement=False)
    assert sorted(m2.numpy()[0].tolist()) == [0, 1, 2, 3, 4]
    # batched (>1 row) input with replacement: rows draw from their own
    # distribution (regression: categorical batch-shape placement)
    probs3 = paddle.to_tensor(np.array(
        [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]], np.float32))
    m3 = random_ops.multinomial(probs3, 50, replacement=True)
    assert m3.shape == [3, 50]
    np.testing.assert_array_equal(m3.numpy(), np.repeat(
        np.array([[0], [1], [2]]), 50, axis=1))
    m1d = random_ops.multinomial(paddle.ones([4]), 3, replacement=True)
    assert m1d.shape == [3]
    lam = paddle.full([500], 4.0)
    ps = random_ops.poisson(lam)
    assert 3.0 < float(ps.numpy().mean()) < 5.0
    tn = random_ops.truncated_normal([1000])
    assert float(np.abs(tn.numpy()).max()) <= 2.01
    d = random_ops.dirichlet(paddle.ones([10, 3]))
    np.testing.assert_allclose(d.numpy().sum(-1), np.ones(10), rtol=1e-5)


# ------------------------------------------------------------ metric ops
def test_accuracy_auc_ops():
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
    label = np.array([1, 0, 0])
    acc = metrics_ops.accuracy(paddle.to_tensor(pred),
                               paddle.to_tensor(label))
    np.testing.assert_allclose(float(acc.numpy()), 2 / 3, rtol=1e-6)
    # AUC oracle vs sklearn-free manual: perfect separation → 1.0
    s = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]],
                 np.float32)
    y = np.array([0, 0, 1, 1])
    a = metrics_ops.auc(paddle.to_tensor(s), paddle.to_tensor(y))
    assert float(a.numpy()) > 0.99


# --------------------------------------------------------------- amp ops
def test_amp_ops():
    from paddle_tpu.ops.amp_ops import (check_finite_and_unscale,
                                        update_loss_scaling)
    g = [paddle.to_tensor(np.array([2.0, 4.0], np.float32))]
    outs, found = check_finite_and_unscale(g, paddle.to_tensor(2.0))
    np.testing.assert_allclose(outs[0].numpy(), [1.0, 2.0])
    assert not bool(found.numpy())
    g_bad = [paddle.to_tensor(np.array([np.inf], np.float32))]
    _, found = check_finite_and_unscale(g_bad, paddle.to_tensor(1.0))
    assert bool(found.numpy())
    s, good = update_loss_scaling(
        [], paddle.to_tensor(True), paddle.to_tensor(1024.0),
        paddle.to_tensor(5), decr_ratio=0.5)
    np.testing.assert_allclose(float(s.numpy()), 512.0)
    assert int(good.numpy()) == 0
    s2, good2 = update_loss_scaling(
        [], paddle.to_tensor(False), paddle.to_tensor(1024.0),
        paddle.to_tensor(1999), incr_every_n_steps=2000, incr_ratio=2.0)
    np.testing.assert_allclose(float(s2.numpy()), 2048.0)
    # decr_every_n_nan_or_inf > 1: first bad step holds the scale, second
    # consecutive bad step decays it (reference state machine)
    s3, good3, bad3 = update_loss_scaling(
        [], paddle.to_tensor(True), paddle.to_tensor(1024.0),
        paddle.to_tensor(7), num_bad_steps=paddle.to_tensor(0),
        decr_every_n_nan_or_inf=2, decr_ratio=0.5)
    np.testing.assert_allclose(float(s3.numpy()), 1024.0)
    assert int(good3.numpy()) == 0 and int(bad3.numpy()) == 1
    s4, good4, bad4 = update_loss_scaling(
        [], paddle.to_tensor(True), s3, good3, num_bad_steps=bad3,
        decr_every_n_nan_or_inf=2, decr_ratio=0.5)
    np.testing.assert_allclose(float(s4.numpy()), 512.0)
    assert int(bad4.numpy()) == 0
    # decay floors at 1.0 (reference clamp) so 1/scale never overflows
    s5, _ = update_loss_scaling(
        [], paddle.to_tensor(True), paddle.to_tensor(1.0),
        paddle.to_tensor(0), decr_ratio=0.5)
    np.testing.assert_allclose(float(s5.numpy()), 1.0)
    # an overflowing bump holds the previous finite scale
    s6, _ = update_loss_scaling(
        [], paddle.to_tensor(False), paddle.to_tensor(3.0e38),
        paddle.to_tensor(1999), incr_every_n_steps=2000, incr_ratio=2.0)
    np.testing.assert_allclose(float(s6.numpy()), 3.0e38)


# ----------------------------------------------------------- sequence ops
def test_sequence_ops():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    lens = np.array([2, 3])
    t, ln = paddle.to_tensor(x), paddle.to_tensor(lens)
    m = sequence_ops.sequence_mask(ln, maxlen=4)
    np.testing.assert_array_equal(m.numpy(),
                                  [[1, 1, 0, 0], [1, 1, 1, 0]])
    s = sequence_ops.sequence_pool(t, ln, "sum")
    np.testing.assert_allclose(s.numpy(), [x[0, :2].sum(0), x[1].sum(0)])
    mx = sequence_ops.sequence_pool(t, ln, "max")
    np.testing.assert_allclose(mx.numpy(), [x[0, :2].max(0), x[1].max(0)])
    last = sequence_ops.sequence_pool(t, ln, "last")
    np.testing.assert_allclose(last.numpy(), [x[0, 1], x[1, 2]])
    sm = sequence_ops.sequence_softmax(paddle.to_tensor(
        np.array([[1., 2., 3.], [1., 1., 1.]], np.float32)),
        paddle.to_tensor(np.array([2, 3])))
    out = sm.numpy()
    assert abs(out[0, :2].sum() - 1) < 1e-5 and out[0, 2] == 0
    rv = sequence_ops.sequence_reverse(t, ln)
    np.testing.assert_allclose(rv.numpy()[0, :2], x[0, 1::-1])
    np.testing.assert_allclose(rv.numpy()[1], x[1, ::-1])
    # pad/unpad roundtrip
    flat = np.arange(10, dtype="float32").reshape(5, 2)
    lens2 = np.array([2, 3])
    padded, _ = sequence_ops.sequence_pad(paddle.to_tensor(flat),
                                          paddle.to_tensor(lens2))
    assert padded.shape == [2, 3, 2]
    np.testing.assert_allclose(padded.numpy()[0, :2], flat[:2])
    np.testing.assert_allclose(padded.numpy()[1], flat[2:])
    back = sequence_ops.sequence_unpad(padded, paddle.to_tensor(lens2))
    np.testing.assert_allclose(back.numpy(), flat)
    ex = sequence_ops.sequence_expand(
        paddle.to_tensor(np.array([[1.], [2.]], np.float32)),
        paddle.to_tensor(np.array([2, 3])))
    np.testing.assert_allclose(ex.numpy().ravel(), [1, 1, 2, 2, 2])


def test_edit_distance():
    d, n = sequence_ops.edit_distance(
        paddle.to_tensor(np.array([[1, 2, 3]])),
        paddle.to_tensor(np.array([[1, 3, 3]])), normalized=False)
    assert float(d.numpy()[0, 0]) == 1.0
    assert int(n.numpy()) == 1


# ---------------------------------------------------------- control flow
def test_control_flow_eager_and_jit():
    # eager
    out = control_flow.while_loop(
        lambda i, s: i < 5,
        lambda i, s: [i + 1, s * 2.0],
        [paddle.to_tensor(0), paddle.to_tensor(1.0)])
    assert int(out[0].numpy()) == 5 and float(out[1].numpy()) == 32.0
    c = control_flow.cond(paddle.to_tensor(True),
                          lambda: paddle.to_tensor(1.0),
                          lambda: paddle.to_tensor(2.0))
    assert float(c.numpy()) == 1.0

    # under jit (lax lowering)
    def fn(x):
        out = control_flow.while_loop(
            lambda i, acc: i < 3,
            lambda i, acc: [i + 1, acc + x.sum()],
            [paddle.to_tensor(0), (x * 0.0).sum()])
        return out[1]
    x = paddle.to_tensor(np.ones(4, np.float32))
    eager = fn(x)
    jitted = paddle.jit.to_static(fn)(x)
    np.testing.assert_allclose(jitted.numpy(), eager.numpy())
    np.testing.assert_allclose(eager.numpy(), 12.0)


def test_case_switch_case():
    v = control_flow.case([(paddle.to_tensor(False), lambda: paddle.ones([1])),
                           (paddle.to_tensor(True), lambda: paddle.zeros([1]))],
                          default=lambda: paddle.full([1], 7.0))
    assert float(v.numpy()[0]) == 0.0
    s = control_flow.switch_case(paddle.to_tensor(2),
                                 {1: lambda: paddle.full([1], 1.0),
                                  2: lambda: paddle.full([1], 2.0)},
                                 default=lambda: paddle.full([1], -1.0))
    assert float(s.numpy()[0]) == 2.0
    s2 = control_flow.switch_case(paddle.to_tensor(9),
                                  {1: lambda: paddle.full([1], 1.0)},
                                  default=lambda: paddle.full([1], -1.0))
    assert float(s2.numpy()[0]) == -1.0


# ------------------------------------------------------------ vision ops
def _roi_align_oracle(x, boxes, out_size, sampling_ratio, aligned):
    """Manual numpy roi_align (the reference roi_align_op.cc algorithm)."""
    N, C, H, W = x.shape
    R = len(boxes)
    s = sampling_ratio
    out = np.zeros((R, C, out_size, out_size), np.float32)

    def bilin(img, y, f):
        y0, x0 = int(np.floor(y)), int(np.floor(f))
        y0c, x0c = min(max(y0, 0), H - 1), min(max(x0, 0), W - 1)
        y1c, x1c = min(y0c + 1, H - 1), min(x0c + 1, W - 1)
        ly, lx = np.clip(y - y0, 0, 1), np.clip(f - x0, 0, 1)
        return (img[:, y0c, x0c] * (1 - ly) * (1 - lx)
                + img[:, y0c, x1c] * (1 - ly) * lx
                + img[:, y1c, x0c] * ly * (1 - lx)
                + img[:, y1c, x1c] * ly * lx)

    off = 0.5 if aligned else 0.0
    for ri, b in enumerate(boxes):
        x0, y0, x1, y1 = b - off
        rw = max(x1 - x0, 1e-6 if aligned else 1.0)
        rh = max(y1 - y0, 1e-6 if aligned else 1.0)
        for oy in range(out_size):
            for ox in range(out_size):
                acc = np.zeros(C, np.float32)
                for sy in range(s):
                    for sx in range(s):
                        yy = y0 + rh / out_size * (oy + (sy + 0.5) / s)
                        xx = x0 + rw / out_size * (ox + (sx + 0.5) / s)
                        acc += bilin(x[0], yy, xx)
                out[ri, :, oy, ox] = acc / (s * s)
    return out


def test_roi_align_matches_manual_oracle():
    x = r(1, 2, 8, 8)
    boxes = np.array([[0.0, 0.0, 4.0, 4.0], [2.0, 2.0, 6.0, 6.0]],
                     np.float32)
    out = vision_ops.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                               paddle.to_tensor(np.array([2])), 2,
                               spatial_scale=1.0, sampling_ratio=2,
                               aligned=True)
    ref = _roi_align_oracle(x, boxes, 2, 2, True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-4)


def test_grid_sample_matches_torch():
    x = r(2, 3, 5, 5)
    grid = np.stack(np.meshgrid(np.linspace(-1, 1, 4),
                                np.linspace(-1, 1, 4), indexing="xy"),
                    axis=-1).astype("float32")
    grid = np.broadcast_to(grid, (2, 4, 4, 2)).copy()
    out = vision_ops.grid_sample(paddle.to_tensor(x),
                                 paddle.to_tensor(grid),
                                 align_corners=True)
    ref = tF.grid_sample(torch.tensor(x), torch.tensor(grid),
                         align_corners=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_affine_grid_matches_torch():
    theta = r(2, 2, 3)
    out = vision_ops.affine_grid(paddle.to_tensor(theta), [2, 3, 4, 5],
                                 align_corners=True)
    ref = tF.affine_grid(torch.tensor(theta), (2, 3, 4, 5),
                         align_corners=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_box_ops():
    a = np.array([[0., 0., 2., 2.]], np.float32)
    b = np.array([[1., 1., 3., 3.], [0., 0., 2., 2.]], np.float32)
    iou = vision_ops.box_iou(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(iou.numpy(), [[1 / 7, 1.0]], rtol=1e-5)
    keep = vision_ops.nms(paddle.to_tensor(b), 0.5,
                          scores=paddle.to_tensor(np.array([0.9, 0.8],
                                                           np.float32)))
    assert keep.numpy().tolist() == [0, 1]  # IoU 1/7 < 0.5: both kept
    dets, nums = vision_ops.multiclass_nms(
        paddle.to_tensor(b[None]),
        paddle.to_tensor(np.array([[[0.1, 0.1], [0.9, 0.85]]], np.float32)))
    assert int(nums.numpy()[0]) >= 1


def test_temporal_shift_pixel_unshuffle_fold():
    x = r(4, 4, 2, 2)  # NT=4 (N=2, T=2)
    out = vision_ops.temporal_shift(paddle.to_tensor(x), seg_num=2,
                                    shift_ratio=0.25)
    assert out.shape == [4, 4, 2, 2]
    y = r(1, 4, 4, 4)
    pu = vision_ops.pixel_unshuffle(paddle.to_tensor(y), 2)
    assert pu.shape == [1, 16, 2, 2]
    # fold∘unfold == multiplicity-weighted identity; with stride=kernel it
    # IS identity
    z = r(1, 2, 4, 4)
    cols = F.unfold(paddle.to_tensor(z), kernel_sizes=2, strides=2)
    back = vision_ops.fold(cols, output_sizes=(4, 4), kernel_sizes=2,
                           strides=2)
    np.testing.assert_allclose(back.numpy(), z, rtol=1e-5)


def test_yolo_box_and_prior_box_shapes():
    x = r(1, 14, 4, 4)  # na=2, class=2 → 2*(5+2)=14
    boxes, scores = vision_ops.yolo_box(
        paddle.to_tensor(x),
        paddle.to_tensor(np.array([[64, 64]], np.int32)),
        anchors=[10, 13, 16, 30], class_num=2, conf_thresh=0.0)
    assert boxes.shape == [1, 32, 4] and scores.shape == [1, 32, 2]
    pb, var = vision_ops.prior_box(
        paddle.to_tensor(r(1, 8, 4, 4)), paddle.to_tensor(r(1, 3, 32, 32)),
        min_sizes=[4.0], aspect_ratios=[2.0], flip=True)
    assert pb.shape[0] == 4 and pb.shape[1] == 4 and pb.shape[3] == 4


# -------------------------------------------------------------- fused ops
def test_fused_ops_match_composed():
    x, w, b = r(3, 4), r(4, 5), r(5)
    out = fused_ops.fused_linear_activation(
        paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b),
        activation="relu")
    np.testing.assert_allclose(out.numpy(), np.maximum(x @ w + b, 0),
                               rtol=1e-5)
    D = 8
    xx = r(2, 5, D)
    ffn = fused_ops.fused_feedforward(
        paddle.to_tensor(xx), paddle.to_tensor(r(D, 16)),
        paddle.to_tensor(r(16)), paddle.to_tensor(r(16, D)),
        paddle.to_tensor(r(D)), pre_layer_norm=True)
    assert ffn.shape == [2, 5, D]
    att = fused_ops.fused_attention(
        paddle.to_tensor(xx), paddle.to_tensor(r(D, 3 * D)),
        paddle.to_tensor(r(3 * D)), paddle.to_tensor(r(D, D)),
        paddle.to_tensor(r(D)), num_heads=2, pre_layer_norm=True)
    assert att.shape == [2, 5, D]
    # fusion_lstm vs rnn semantics smoke + numerics sanity
    hs, hT, cT = fused_ops.fusion_lstm(
        paddle.to_tensor(r(2, 3, 4)), paddle.to_tensor(r(4, 16)),
        paddle.to_tensor(r(4, 16)))
    assert hs.shape == [2, 3, 4] and hT.shape == [2, 4]
    gs, gT = fused_ops.fusion_gru(
        paddle.to_tensor(r(2, 3, 4)), paddle.to_tensor(r(4, 12)),
        paddle.to_tensor(r(4, 12)))
    assert gs.shape == [2, 3, 4]
    emb = fused_ops.fused_embedding_seq_pool(
        paddle.to_tensor(r(10, 4)),
        paddle.to_tensor(np.array([[1, 2, 0], [3, 0, 0]])),
        paddle.to_tensor(np.array([2, 1])), combiner="sum")
    assert emb.shape == [2, 4]


def test_coalesce_tensor():
    xs = [paddle.ones([2, 2]), paddle.zeros([3])]
    views, flat = fused_ops.coalesce_tensor(xs)
    assert flat.shape == [7]
    np.testing.assert_array_equal(views[0].numpy(), np.ones((2, 2)))


# -------------------------------------------------------------- quant ops
def test_fake_quant_roundtrip_and_ste():
    x = paddle.to_tensor(r(4, 4), stop_gradient=False)
    out, scale = quant_ops.fake_quantize_dequantize_abs_max(x)
    assert float(np.abs(out.numpy() - x.numpy()).max()) <= \
        float(scale.numpy()) / 127 + 1e-6
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones((4, 4)), rtol=1e-6)

    q, s = quant_ops.fake_quantize_abs_max(paddle.to_tensor(r(3, 3)))
    assert float(np.abs(q.numpy()).max()) <= 127
    cq, cs = quant_ops.fake_channel_wise_quantize_abs_max(
        paddle.to_tensor(r(4, 3)), quant_axis=0)
    assert cs.shape == [4]
    qz = quant_ops.quantize_linear(paddle.to_tensor(r(2, 2)),
                                   paddle.to_tensor(0.05))
    dz = quant_ops.dequantize_linear(qz, paddle.to_tensor(0.05))
    assert dz.shape == [2, 2]


# ---------------------------------------------------------- optimizer ops
def test_optimizer_ops_match_classes():
    import jax.numpy as jnp
    p = jnp.asarray(r(4))
    g = jnp.asarray(r(4))
    out = optimizer_ops.sgd_step(p, g, 0.1)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(p) - 0.1 * np.asarray(g),
                               rtol=1e-6)
    new_p, m2, v2, b1, b2 = optimizer_ops.adam_step(
        p, g, jnp.zeros(4), jnp.zeros(4), jnp.asarray(1.0),
        jnp.asarray(1.0), 0.01)
    # one torch oracle step
    tp = torch.tensor(np.asarray(p), requires_grad=True)
    opt = torch.optim.Adam([tp], lr=0.01, eps=1e-8)
    tp.grad = torch.tensor(np.asarray(g))
    opt.step()
    np.testing.assert_allclose(new_p.numpy(), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- extra ops
def test_extra_losses():
    x, y = r(3, 4), r(3, 4)
    lab = np.array([1, 0, 2])
    hl = extra_ops.hinge_loss(paddle.to_tensor(x),
                              paddle.to_tensor((y > 0).astype("float32")))
    assert hl.shape == [3, 4]
    rl = extra_ops.rank_loss(paddle.to_tensor(np.ones((3, 1), np.float32)),
                             paddle.to_tensor(r(3, 1)),
                             paddle.to_tensor(r(3, 1)))
    assert (rl.numpy() >= 0).all()
    bl = extra_ops.bpr_loss(paddle.to_tensor(x), paddle.to_tensor(lab))
    assert bl.shape == [3, 1]
    fl = extra_ops.sigmoid_focal_loss(
        paddle.to_tensor(x), paddle.to_tensor((y > 0).astype("float32")))
    ref = torchvision_focal(x, (y > 0).astype("float32"))
    np.testing.assert_allclose(fl.numpy(), ref, rtol=1e-4, atol=1e-5)
    cs = extra_ops.cos_sim(paddle.to_tensor(x), paddle.to_tensor(y))
    ref_cs = (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                * np.linalg.norm(y, axis=-1))
    np.testing.assert_allclose(cs.numpy()[:, 0], ref_cs, rtol=1e-4)
    np.testing.assert_allclose(
        float(extra_ops.squared_l2_norm(paddle.to_tensor(x)).numpy()),
        (x ** 2).sum(), rtol=1e-5)


def torchvision_focal(x, y, alpha=0.25, gamma=2.0):
    """Manual focal-loss oracle (RetinaNet formula, float64)."""
    x = x.astype(np.float64)
    y = y.astype(np.float64)
    p = 1 / (1 + np.exp(-x))
    ce = np.logaddexp(0.0, x) - x * y
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    return (a_t * (1 - p_t) ** gamma * ce).astype(np.float32)


def test_extra_layout_and_misc():
    x = r(1, 2, 4, 4)
    sd = extra_ops.space_to_depth(paddle.to_tensor(x), 2)
    assert sd.shape == [1, 8, 2, 2]
    seg = extra_ops.segment_sum(
        paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                  np.float32)),
        paddle.to_tensor(np.array([0, 0, 1])))
    np.testing.assert_allclose(seg.numpy(), [[4, 6], [5, 6]])
    segm = extra_ops.segment_mean(
        paddle.to_tensor(np.array([[2., 2.], [4., 4.], [6., 6.]],
                                  np.float32)),
        paddle.to_tensor(np.array([0, 0, 1])))
    np.testing.assert_allclose(segm.numpy(), [[3, 3], [6, 6]])
    mx = extra_ops.multiplex(
        [paddle.to_tensor(np.ones((2, 3), np.float32)),
         paddle.to_tensor(np.zeros((2, 3), np.float32))],
        paddle.to_tensor(np.array([1, 0])))
    np.testing.assert_allclose(mx.numpy(), [[0, 0, 0], [1, 1, 1]])
    m = extra_ops.mul(paddle.to_tensor(r(2, 3, 4)),
                      paddle.to_tensor(r(12, 5)), x_num_col_dims=1)
    assert m.shape == [2, 5]
    pc = extra_ops.partial_sum([paddle.to_tensor(np.ones((2, 4), np.float32)),
                                paddle.to_tensor(np.ones((2, 4), np.float32))],
                               start_index=1, length=2)
    np.testing.assert_allclose(pc.numpy(), np.full((2, 2), 2.0))
    sn = extra_ops.spectral_norm(paddle.to_tensor(r(4, 4)), power_iters=20)
    u, s, v = np.linalg.svd(np.asarray(sn.numpy()))
    assert s.max() < 1.3  # sigma_max normalized toward 1


def test_gather_tree_and_beam_step():
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]])      # [T=3, B=1, beam=2]
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]])
    out = extra_ops.gather_tree(paddle.to_tensor(ids),
                                paddle.to_tensor(parents))
    # beam 0 at t=2 came from parent 1: path 2,4? backtrack: t2 beam0
    # parent=1 → t1 beam1=4, its parent 0 → t0 beam0=1
    np.testing.assert_array_equal(out.numpy()[:, 0, 0], [1, 4, 5])
    lp = paddle.to_tensor(np.log(np.array(
        [[[0.7, 0.2, 0.1], [0.5, 0.3, 0.2]]], np.float32)))
    sc = paddle.to_tensor(np.zeros((1, 2), np.float32))
    ns, par, tok = extra_ops.beam_search_step(lp, sc, 2)
    assert tok.numpy()[0, 0] == 0 and par.numpy()[0, 0] == 0


def test_crf_and_viterbi():
    B, T, C = 2, 4, 3
    em = r(B, T, C)
    trans_full = r(C + 2, C)
    lens = np.array([4, 3])
    nll = extra_ops.linear_chain_crf(
        paddle.to_tensor(em), paddle.to_tensor(trans_full),
        paddle.to_tensor(np.array([[0, 1, 2, 1], [2, 0, 1, 0]])),
        paddle.to_tensor(lens))
    assert (nll.numpy() > 0).all()  # NLL of one path < total mass
    scores, path = extra_ops.viterbi_decode(
        paddle.to_tensor(em), paddle.to_tensor(trans_full[2:]),
        paddle.to_tensor(lens))
    assert path.shape == [B, T]
    # brute-force oracle for row 0 (length 4, no bos/eos)
    best, best_path = -1e9, None
    import itertools
    for p in itertools.product(range(C), repeat=T):
        s = em[0, 0, p[0]] + sum(
            trans_full[2:][p[i - 1], p[i]] + em[0, i, p[i]]
            for i in range(1, T))
        if s > best:
            best, best_path = s, p
    np.testing.assert_allclose(float(scores.numpy()[0]), best, rtol=1e-4)
    np.testing.assert_array_equal(path.numpy()[0], best_path)


def test_sync_batch_norm_functional():
    x = r(4, 3, 2, 2)
    rm = paddle.to_tensor(np.zeros(3, np.float32))
    rv = paddle.to_tensor(np.ones(3, np.float32))
    out = F.sync_batch_norm(paddle.to_tensor(x), rm, rv, training=True)
    # outside any mesh scope == plain batch norm stats
    mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(
        out.numpy().mean(axis=(0, 2, 3)), np.zeros(3), atol=1e-5)
    np.testing.assert_allclose(rm.numpy(), 0.1 * mean, rtol=1e-4)


def test_math_tail():
    np.testing.assert_allclose(
        paddle.ops.math.complex(paddle.to_tensor(np.float32(1)),
                                paddle.to_tensor(np.float32(2))).numpy(),
        1 + 2j)
    x = r(3, 5)
    np.testing.assert_allclose(paddle.ops.math.diff(
        paddle.to_tensor(x)).numpy(), np.diff(x), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.ops.math.trapezoid(paddle.to_tensor(x)).numpy(),
        np.trapezoid(x) if hasattr(np, "trapezoid") else np.trapz(x),
        rtol=1e-5)
    lg = paddle.ops.math.logit(paddle.to_tensor(
        np.array([0.2, 0.5, 0.8], np.float32)))
    np.testing.assert_allclose(lg.numpy(),
                               np.log([0.25, 1.0, 4.0]), rtol=1e-4)
    v = paddle.ops.math.vander(paddle.to_tensor(
        np.array([1., 2., 3.], np.float32)), 3)
    np.testing.assert_allclose(v.numpy(), np.vander([1, 2, 3], 3))
    t = paddle.ops.math.take(paddle.to_tensor(x),
                             paddle.to_tensor(np.array([0, 6, -1])))
    np.testing.assert_allclose(t.numpy(), x.ravel()[[0, 6, -1]])
    n2n = paddle.ops.math.nan_to_num(paddle.to_tensor(
        np.array([np.nan, np.inf], np.float32)))
    assert np.isfinite(n2n.numpy()).all()
