"""Native C++ DataFeed tests (reference: test_dataset.py — slot files →
InMemoryDataset → load/shuffle/batch → train_from_dataset)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import InMemoryDataset


def _write_slot_file(path, rows, rng):
    """MultiSlot text format: per slot `<n> v1 ... vn` (reference
    data_feed.cc MultiSlotDataFeed line format). Slots: ids(u), feat(f),
    label(f)."""
    with open(path, "w") as f:
        recs = []
        for _ in range(rows):
            n_ids = rng.randint(1, 5)
            ids = rng.randint(0, 50, n_ids)
            feat = rng.randn(3)
            label = [float(ids.sum() % 2)]
            f.write(f"{n_ids} " + " ".join(map(str, ids)) + " "
                    + "3 " + " ".join(f"{v:.6f}" for v in feat) + " "
                    + "1 " + f"{label[0]}" + "\n")
            recs.append((ids, feat, label))
    return recs


@pytest.fixture
def slot_files(tmp_path):
    rng = np.random.RandomState(0)
    recs = []
    paths = []
    for i in range(3):
        p = str(tmp_path / f"part-{i}.txt")
        recs += _write_slot_file(p, 20, rng)
        paths.append(p)
    return paths, recs


def _make_ds(paths, batch_size=8):
    ds = InMemoryDataset()
    ds.set_use_var([("ids", "int64"), ("feat", "float32"),
                    ("label", "float32")])
    ds.set_filelist(paths)
    ds.set_batch_size(batch_size)
    ds.set_thread(3)
    return ds


def test_load_parse_and_values(slot_files):
    paths, recs = slot_files
    ds = _make_ds(paths)
    n = ds.load_into_memory()
    assert n == 60 == ds.get_memory_data_size()
    assert ds.memory_bytes() > 0
    batches = list(ds.batches())
    assert sum(b["ids"][0].shape[0] for b in batches) == 60
    # unshuffled first record matches file order
    b0 = batches[0]
    ids0, len0 = b0["ids"]
    np.testing.assert_array_equal(ids0[0, :len0[0]], recs[0][0])
    np.testing.assert_allclose(b0["feat"][0][0], recs[0][1], rtol=1e-5)
    np.testing.assert_allclose(b0["label"][0][0, 0], recs[0][2][0])
    # ragged ids are padded with 0 beyond the length
    assert (ids0[0, len0[0]:] == 0).all()


def test_shuffle_permutes_but_preserves_set(slot_files):
    paths, recs = slot_files
    ds = _make_ds(paths, batch_size=60)
    ds.load_into_memory()
    before = next(iter(ds.batches()))["label"][0].ravel().copy()
    ds.local_shuffle(seed=7)
    after = next(iter(ds.batches()))["label"][0].ravel()
    assert not np.array_equal(before, after)
    np.testing.assert_allclose(np.sort(before), np.sort(after))


def test_malformed_file_reports_error(tmp_path):
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as f:
        f.write("2 1\n")  # declares 2 ids, provides 1
    ds = _make_ds([p])
    ds.set_use_var([("ids", "int64")])
    with pytest.raises(RuntimeError, match="malformed"):
        ds.load_into_memory()


def test_release_memory(slot_files):
    paths, _ = slot_files
    ds = _make_ds(paths)
    ds.load_into_memory()
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_train_from_dataset(tmp_path, slot_files):
    """Static program trained from the native dataset (reference:
    test_dataset.py train_from_dataset flow)."""
    paths, _ = slot_files
    ds = _make_ds(paths, batch_size=20)
    ds.set_pad_value("ids", 0)
    ds.load_into_memory()

    paddle.static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                feat = paddle.static.data("feat", [-1, 3], "float32")
                label = paddle.static.data("label", [-1, 1], "float32")
                lin = paddle.nn.Linear(3, 1)
                loss = ((lin(feat) - label) ** 2).mean()
                opt = paddle.optimizer.SGD(0.1)
                opt.minimize(loss)
                exe = paddle.static.Executor()
                exe.run(startup)
                first = None
                for _ in range(5):
                    res = exe.train_from_dataset(main, ds,
                                                 fetch_list=[loss])
                    if first is None:
                        first = float(np.asarray(res[0]))
                last = float(np.asarray(res[0]))
                assert last < first
        finally:
            paddle.disable_static()


def test_queue_dataset_true_streaming_bounded_memory(tmp_path):
    """VERDICT r2 item 6: parser threads fill a bounded record queue while
    batches are consumed; the queue high-water mark must respect the
    capacity even for a dataset much larger than it (reference:
    framework/data_set.cc QueueDataset channel)."""
    from paddle_tpu.io.dataset_native import QueueDataset

    # 2000 records across 4 files, capacity 64 records
    files = []
    for fi in range(4):
        p = tmp_path / f"part-{fi}.txt"
        with open(p, "w") as f:
            for r in range(500):
                f.write(f"1 {fi * 500 + r} 2 0.5 1.5\n")
        files.append(str(p))

    ds = QueueDataset(queue_capacity=64)
    ds.set_use_var([("ids", "int64"), ("vals", "float32")])
    ds.set_batch_size(32)
    ds.set_thread(4)
    ds.set_filelist(files)

    seen_ids = []
    n_batches = 0
    for batch in ds.batches():
        ids, id_lens = batch["ids"]
        vals, val_lens = batch["vals"]
        assert vals.shape[1] == 2 and (val_lens == 2).all()
        seen_ids.extend(ids[:, 0].tolist())
        n_batches += 1
    assert n_batches == 2000 // 32 + 1
    assert sorted(seen_ids) == list(range(2000))   # every record, once
    peak = ds.queue_peak_depth()
    assert 0 < peak <= 64, peak                    # bounded by capacity

    # streaming mode refuses the in-memory surface loudly
    import pytest
    with pytest.raises(RuntimeError):
        ds.load_into_memory()
    with pytest.raises(RuntimeError):
        ds.local_shuffle()

    # second pass works (fresh stream)
    assert sum(1 for _ in ds.batches()) == n_batches


def test_data_generator_authors_native_format(tmp_path):
    """fleet.data_generator writes the MultiSlot text the native feed
    parses (reference data_generator.py:1 contract)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.io.dataset_native import InMemoryDataset

    class CtrGen(fleet.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                fid, label = line.strip().split(",")
                yield [("feat", [int(fid), int(fid) + 1]),
                       ("label", [int(label)])]
            return gen
    g = CtrGen()
    path = g.run_to_file(["3,1", "7,0", "11,1"], str(tmp_path / "out.txt"))
    text = open(path).read().splitlines()
    assert text[0] == "2 3 4 1 1"
    assert g.slots() == ["feat", "label"]

    ds = InMemoryDataset()
    ds.set_use_var([("feat", "int64"), ("label", "int64")])
    ds.set_filelist([path])
    ds.set_batch_size(3)
    assert ds.load_into_memory() == 3
    batch = next(ds.batches())
    np.testing.assert_array_equal(batch["feat"][0],
                                  [[3, 4], [7, 8], [11, 12]])
    np.testing.assert_array_equal(batch["label"][0].ravel(), [1, 0, 1])

    # slot-order drift is rejected
    class BadGen(fleet.MultiSlotDataGenerator):
        def __init__(self):
            super().__init__()
            self.n = 0
        def generate_sample(self, line):
            def gen():
                self.n += 1
                if self.n == 1:
                    yield [("a", [1]), ("b", [2])]
                else:
                    yield [("b", [2]), ("a", [1])]
            return gen
    with pytest.raises(ValueError):
        BadGen().run_to_file(["x", "y"], str(tmp_path / "bad.txt"))


def test_cpp_train_demo_builds_and_converges(tmp_path):
    """C40 (reference fluid/train/demo/demo_trainer.cc): training driven
    entirely from a standalone C++ program embedding the runtime."""
    import os
    import shutil
    import subprocess
    import sysconfig

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    if not sysconfig.get_config_var("Py_ENABLE_SHARED") \
            or not sysconfig.get_config_var("LIBDIR"):
        pytest.skip("python built without a shared libpython")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "paddle_tpu", "native", "demo",
                       "train_demo.cc")
    exe = str(tmp_path / "train_demo")
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sysconfig.get_config_var('py_version_short')}"
    build = subprocess.run(
        ["g++", "-O2", "-std=c++17", src, f"-I{inc}", f"-L{libdir}",
         f"-Wl,-rpath,{libdir}", f"-l{pyver}", "-o", exe],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    run = subprocess.run([exe], capture_output=True, text=True, env=env,
                         timeout=300)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "C++ train demo OK" in run.stdout


def test_model_encryption_aes(tmp_path):
    """C41 tail (reference pybind/crypto.cc): AES model encryption —
    FIPS-197 vectors + ciphertext-at-rest round trip of a real
    checkpoint."""
    import ctypes

    import paddle_tpu as paddle
    from paddle_tpu.native import crypto_so_path
    from paddle_tpu.utils.crypto import AESCipher, CipherFactory

    L = ctypes.CDLL(crypto_so_path())
    L.aes_encrypt_block.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_char_p, ctypes.c_char_p]
    out = ctypes.create_string_buffer(16)
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    L.aes_encrypt_block(bytes(range(16)), 16, pt, out)
    assert out.raw.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    L.aes_encrypt_block(bytes(range(32)), 32, pt, out)
    assert out.raw.hex() == "8ea2b7ca516745bfeafc49904b496089"

    # a real model checkpoint, encrypted at rest
    m = paddle.nn.Linear(4, 2)
    plain = tmp_path / "model.pdparams"
    paddle.save(m.state_dict(), str(plain))
    cipher = CipherFactory.create_cipher(key="secret-key")
    enc = tmp_path / "model.enc"
    cipher.encrypt_to_file(plain.read_bytes(), str(enc))
    assert enc.read_bytes() != plain.read_bytes()
    dec = tmp_path / "model.dec"
    dec.write_bytes(cipher.decrypt_from_file(str(enc)))
    state = paddle.load(str(dec))
    np.testing.assert_array_equal(state["weight"].numpy(),
                                  m.weight.numpy())
    # wrong key: garbage bytes, never the plaintext
    wrong = AESCipher("other").decrypt(enc.read_bytes())
    assert wrong != plain.read_bytes()
    with pytest.raises(ValueError):
        AESCipher("k").decrypt(b"not an artifact")
