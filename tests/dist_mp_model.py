"""Multi-process distributed training script (reference:
fluid/tests/unittests/dist_mnist.py — the model file TestDistBase launches
in trainer subprocesses). Run via paddle_tpu.distributed.launch.

Results are written to $DIST_OUT_DIR/rank<r>.json (one file per rank) —
NOT parsed from stdout: child stdout lines from concurrent ranks interleave
through the launcher pipe, which made stdout parsing flake under load.
Also exercises the point-to-point and collective surface (all_gather,
reduce_scatter, send/recv ring) so the cross-process paths beyond
allreduce are covered.
"""
import json
import os

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
from paddle_tpu.parallel import ShardedTrainStep, shard_batch  # noqa: E402

GLOBAL_BATCH = 16
STEPS = 5


def make_data(step):
    """Deterministic global batch, identical in every process."""
    rs = np.random.RandomState(1234 + step)
    X = rs.randn(GLOBAL_BATCH, 8).astype("float32")
    Y = rs.randn(GLOBAL_BATCH, 2).astype("float32")
    return X, Y


def build():
    with paddle.utils.unique_name.guard():
        paddle.seed(42)
        model = paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
            paddle.nn.Linear(16, 2))
        optim = opt.Momentum(0.05, parameters=model.parameters())
    return model, optim


def loss_fn(m, x, y):
    return ((m(x) - y) ** 2).mean()


def collective_probe(rank, world):
    """all_gather / reduce_scatter / send+recv ring results for the parent
    to assert on."""
    dist = paddle.distributed
    out = {}
    # all_gather: every rank contributes [rank, rank+0.5]
    mine = paddle.to_tensor(np.array([rank, rank + 0.5], np.float32))
    gathered = []
    dist.all_gather(gathered, mine)
    out["all_gather"] = [np.asarray(g.numpy()).tolist() for g in gathered]
    if world > 1:
        # reduce_scatter: each rank contributes [rank + 0, ..., rank + w-1];
        # rank r keeps sum over ranks of chunk r
        full = paddle.to_tensor(np.arange(world, dtype=np.float32) + rank)
        rs_out = paddle.to_tensor(np.zeros(1, np.float32))
        dist.reduce_scatter(rs_out, full)
        out["reduce_scatter"] = np.asarray(
            rs_out.numpy()).reshape(-1).tolist()
        # send/recv ring: rank r sends its id to (r+1) % world
        nxt = (rank + 1) % world
        prv = (rank - 1) % world
        token = paddle.to_tensor(np.array([float(rank)], np.float32))
        if rank % 2 == 0:
            dist.send(token, dst=nxt)
            got = dist.recv(src=prv, shape=[1], dtype="float32")
        else:
            got = dist.recv(src=prv, shape=[1], dtype="float32")
            dist.send(token, dst=nxt)
        out["ring_recv"] = float(np.asarray(got.numpy())[0])
        # bf16 ring: the raw-buffer p2p framing must round-trip the
        # ml_dtypes extension types by NAME ('<V2' .str does not)
        tok16 = paddle.to_tensor(
            np.array([float(rank)], np.float32)).astype("bfloat16")
        if rank % 2 == 0:
            dist.send(tok16, dst=nxt)
            got16 = dist.recv(src=prv, shape=[1], dtype="bfloat16")
        else:
            got16 = dist.recv(src=prv, shape=[1], dtype="bfloat16")
            dist.send(tok16, dst=nxt)
        out["ring_recv_bf16"] = float(np.asarray(
            got16.astype("float32").numpy())[0])
    return out


def main():
    env = paddle.distributed.init_parallel_env()
    rank, world = env.rank, env.world_size
    assert jax.device_count() == world, (jax.device_count(), world)

    model, optim = build()
    step = ShardedTrainStep(model, loss_fn, optim)
    paddle.distributed.barrier()  # real cross-process rendezvous
    losses = []
    per_rank = GLOBAL_BATCH // world
    for i in range(STEPS):
        X, Y = make_data(i)
        # each process feeds ONLY its shard of the global batch
        xs = shard_batch(X[rank * per_rank:(rank + 1) * per_rank])
        ys = shard_batch(Y[rank * per_rank:(rank + 1) * per_rank])
        losses.append(float(step(xs, ys).numpy()))
    rec = {"rank": rank, "losses": losses}
    rec.update(collective_probe(rank, world))
    out_dir = os.environ.get("DIST_OUT_DIR")
    if out_dir:
        path = os.path.join(out_dir, f"rank{rank}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(rec, f)
        os.replace(path + ".tmp", path)  # atomic publish
    print("DIST_LOSSES " + json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
