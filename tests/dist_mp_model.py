"""2-process distributed training script (reference:
fluid/tests/unittests/dist_mnist.py — the model file TestDistBase launches
in trainer subprocesses). Run via paddle_tpu.distributed.launch; prints one
JSON line of per-step losses for the parent test to compare."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
from paddle_tpu.parallel import ShardedTrainStep, shard_batch  # noqa: E402

GLOBAL_BATCH = 16
STEPS = 5


def make_data(step):
    """Deterministic global batch, identical in every process."""
    rs = np.random.RandomState(1234 + step)
    X = rs.randn(GLOBAL_BATCH, 8).astype("float32")
    Y = rs.randn(GLOBAL_BATCH, 2).astype("float32")
    return X, Y


def build():
    with paddle.utils.unique_name.guard():
        paddle.seed(42)
        model = paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
            paddle.nn.Linear(16, 2))
        optim = opt.Momentum(0.05, parameters=model.parameters())
    return model, optim


def loss_fn(m, x, y):
    return ((m(x) - y) ** 2).mean()


def main():
    env = paddle.distributed.init_parallel_env()
    rank, world = env.rank, env.world_size
    assert jax.device_count() == world, (jax.device_count(), world)

    model, optim = build()
    step = ShardedTrainStep(model, loss_fn, optim)
    paddle.distributed.barrier()  # real cross-process rendezvous
    losses = []
    per_rank = GLOBAL_BATCH // world
    for i in range(STEPS):
        X, Y = make_data(i)
        # each process feeds ONLY its shard of the global batch
        xs = shard_batch(X[rank * per_rank:(rank + 1) * per_rank])
        ys = shard_batch(Y[rank * per_rank:(rank + 1) * per_rank])
        losses.append(float(step(xs, ys).numpy()))
    print("DIST_LOSSES " + json.dumps({"rank": rank, "losses": losses}),
          flush=True)


if __name__ == "__main__":
    main()
