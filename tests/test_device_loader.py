"""DeviceLoader: async host->device double buffering (the reference's
LoDTensorBlockingQueue overlap role, fluid/reader.py:149)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, DeviceLoader, Dataset


class _DS(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((3,), i, dtype=np.float32),
                np.asarray(i, dtype=np.int64))


def test_device_loader_preserves_order_and_values():
    dl = DataLoader(_DS(), batch_size=4, shuffle=False)
    seen = []
    for x, y in DeviceLoader(dl, size=2):
        assert isinstance(x, paddle.Tensor) and isinstance(y, paddle.Tensor)
        assert x.shape == [4, 3]
        seen.extend(int(v) for v in y.numpy())
    assert seen == list(range(20))


def test_device_loader_nested_structures_and_len():
    class _DictDS(Dataset):
        def __len__(self):
            return 6

        def __getitem__(self, i):
            return {"img": np.ones((2, 2), np.float32) * i,
                    "meta": [np.asarray(i), np.asarray(-i)]}

    dl = DataLoader(_DictDS(), batch_size=2, shuffle=False)
    dvl = DeviceLoader(dl, size=3)
    assert len(dvl) == len(dl) == 3
    batches = list(dvl)
    assert len(batches) == 3
    b0 = batches[0]
    assert isinstance(b0, dict)
    assert isinstance(b0["img"], paddle.Tensor)
    assert isinstance(b0["meta"][0], paddle.Tensor)
    np.testing.assert_allclose(batches[1]["img"].numpy()[0],
                               np.ones((2, 2)) * 2)


def test_device_loader_trains_a_model():
    """End-to-end: DeviceLoader feeding a jitted train step must converge
    exactly like plain DataLoader feeding (same batches, same arithmetic)."""
    paddle.seed(0)
    model = paddle.nn.Linear(3, 1)
    optim = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda m, x, y: ((m(x) - y) ** 2).mean(), optim)
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)

    class _Reg(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            x = rng.randn(3).astype(np.float32)
            return x, (x @ w_true).astype(np.float32)

    losses = []
    for _epoch in range(30):
        for x, y in DeviceLoader(DataLoader(_Reg(), batch_size=32)):
            losses.append(float(step(x, y).numpy()))
    assert losses[-1] < 0.01 * losses[0] + 1e-6, losses[-5:]


def test_device_loader_namedtuple_batches():
    """namedtuple batches must be rebuilt field-wise — type(item)(generator)
    passes one generator to the constructor and crashes."""
    import collections
    Batch = collections.namedtuple("Batch", ["img", "label"])

    def collate(samples):
        from paddle_tpu.io import default_collate_fn
        x, y = default_collate_fn(samples)
        return Batch(img=x, label=y)

    dl = DataLoader(_DS(8), batch_size=2, collate_fn=collate)
    seen = []
    for b in DeviceLoader(dl):
        assert isinstance(b, Batch)
        assert isinstance(b.img, paddle.Tensor)
        seen.extend(int(v) for v in b.label.numpy())
    assert seen == list(range(8))


def test_device_loader_size_validation():
    with pytest.raises(ValueError):
        DeviceLoader([], size=0)
