"""Static-graph pillar tests.

Mirrors the reference's static coverage style
(/root/reference/python/paddle/fluid/tests/unittests/test_executor_*.py,
test_program.py, test_cond.py, test_while_loop_op.py): capture, Executor
feed/fetch, append_backward training, dygraph parity, control flow,
save/load, inference export.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(autouse=True)
def _static_mode():
    main = paddle.static.Program()
    startup = paddle.static.Program()
    # fresh scope + name counters per test: auto-generated param names must
    # not collide with variables an earlier test initialized in the global
    # scope (reference tests use scope_guard/unique_name.guard the same way)
    paddle.static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        with paddle.static.program_guard(main, startup):
            yield main, startup
        paddle.disable_static()


def _exe():
    return paddle.static.Executor()


def test_capture_and_run():
    x = paddle.static.data("x", [4], "float32")
    y = x * 2.0 + 1.0
    prog = paddle.static.default_main_program()
    assert len(prog.ops) >= 1
    exe = _exe()
    (out,) = exe.run(prog, feed={"x": np.arange(4, dtype=np.float32)},
                     fetch_list=[y])
    np.testing.assert_allclose(out, np.arange(4) * 2.0 + 1.0)


def test_feed_shape_flex_and_cache():
    x = paddle.static.data("x", [-1, 3], "float32")
    y = (x * x).sum()
    exe = _exe()
    for n in (2, 5):
        a = np.random.randn(n, 3).astype(np.float32)
        (out,) = exe.run(feed={"x": a}, fetch_list=[y])
        np.testing.assert_allclose(out, (a * a).sum(), rtol=1e-5)


def test_missing_feed_raises():
    x = paddle.static.data("x", [2], "float32")
    y = x + 1.0
    with pytest.raises(ValueError, match="feed is missing"):
        _exe().run(fetch_list=[y])


def test_uninitialized_param_raises():
    lin = paddle.nn.Linear(3, 2)
    x = paddle.static.data("x", [-1, 3], "float32")
    out = lin(x)
    with pytest.raises(RuntimeError, match="not initialized"):
        _exe().run(feed={"x": np.zeros((1, 3), np.float32)},
                    fetch_list=[out])


def test_append_backward_and_sgd_training():
    x = paddle.static.data("x", [8, 3], "float32")
    y = paddle.static.data("y", [8, 1], "float32")
    lin = paddle.nn.Linear(3, 1)
    loss = ((lin(x) - y) ** 2).mean()
    params_grads = paddle.static.append_backward(loss)
    assert len(params_grads) == 2
    assert params_grads[0][1].name.endswith("@GRAD")

    prog = paddle.static.default_main_program()
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    w = np.array([[2.0], [-1.0], [0.5]], np.float32)
    xs = np.random.randn(8, 3).astype(np.float32)
    ys = xs @ w
    # fetch grads directly (no optimizer): check vs analytic
    g_names = [g.name for _, g in params_grads]
    outs = exe.run(prog, feed={"x": xs, "y": ys},
                   fetch_list=[loss] + g_names)
    assert np.isfinite(outs[0])


def test_static_matches_dygraph_losses():
    """Same weights + same data → identical loss trajectory in both modes
    (reference: TestDistBase-style parity checking)."""
    np.random.seed(0)
    xs = np.random.randn(16, 4).astype(np.float32)
    w = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    ys = (xs @ w + 0.3).astype(np.float32)
    w0 = np.random.randn(4, 8).astype(np.float32) * 0.1
    b0 = np.zeros(8, np.float32)
    w1 = np.random.randn(8, 1).astype(np.float32) * 0.1
    b1 = np.zeros(1, np.float32)

    # ---- static
    x = paddle.static.data("x", [16, 4], "float32")
    y = paddle.static.data("y", [16, 1], "float32")
    l1 = paddle.nn.Linear(4, 8)
    l2 = paddle.nn.Linear(8, 1)
    h = paddle.nn.functional.relu(l1(x))
    loss = ((l2(h) - y) ** 2).mean()
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    scope = paddle.static.global_scope()
    import jax.numpy as jnp
    scope.set(l1.weight.name, jnp.asarray(w0))
    scope.set(l1.bias.name, jnp.asarray(b0))
    scope.set(l2.weight.name, jnp.asarray(w1))
    scope.set(l2.bias.name, jnp.asarray(b1))
    static_losses = []
    for _ in range(5):
        (lv,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        static_losses.append(float(lv))

    # ---- dygraph
    paddle.disable_static()
    try:
        dl1 = paddle.nn.Linear(4, 8)
        dl2 = paddle.nn.Linear(8, 1)
        dl1.weight.set_value(w0)
        dl1.bias.set_value(b0)
        dl2.weight.set_value(w1)
        dl2.bias.set_value(b1)
        dopt = paddle.optimizer.SGD(
            learning_rate=0.1,
            parameters=list(dl1.parameters()) + list(dl2.parameters()))
        dyg_losses = []
        for _ in range(5):
            out = dl2(paddle.nn.functional.relu(dl1(paddle.to_tensor(xs))))
            l = ((out - paddle.to_tensor(ys)) ** 2).mean()
            l.backward()
            dopt.step()
            dopt.clear_grad()
            dyg_losses.append(float(l.numpy()))
    finally:
        paddle.enable_static()

    np.testing.assert_allclose(static_losses, dyg_losses, rtol=1e-4)


def test_adam_training_converges():
    x = paddle.static.data("x", [32, 10], "float32")
    y = paddle.static.data("y", [32, 1], "int64")
    lin = paddle.nn.Linear(10, 4)
    loss = paddle.nn.functional.cross_entropy(lin(x), y)
    paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    np.random.seed(1)
    xs = np.random.randn(32, 10).astype(np.float32)
    ys = np.random.randint(0, 4, (32, 1)).astype(np.int64)
    losses = [float(exe.run(feed={"x": xs, "y": ys},
                            fetch_list=[loss])[0]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.3


def test_lr_scheduler_no_recompile():
    x = paddle.static.data("x", [4, 2], "float32")
    lin = paddle.nn.Linear(2, 1)
    loss = lin(x).mean()
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched)
    opt.minimize(loss)
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    xs = np.ones((4, 2), np.float32)
    exe.run(feed={"x": xs}, fetch_list=[loss])
    n_compiled = len(exe._cache)
    sched.step()
    exe.run(feed={"x": xs}, fetch_list=[loss])
    assert len(exe._cache) == n_compiled  # lr is a runtime input


def test_batch_norm_updates_running_stats():
    x = paddle.static.data("x", [8, 3], "float32")
    bn = paddle.nn.BatchNorm1D(3)
    out = bn(x)
    mean_name = bn._mean.name
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    xs = (np.random.randn(8, 3) * 2 + 5).astype(np.float32)
    exe.run(feed={"x": xs}, fetch_list=[out])
    scope = paddle.static.global_scope()
    rm = np.asarray(scope.find_var(mean_name))
    expect = 0.1 * xs.mean(0)  # momentum 0.9, started at zeros
    np.testing.assert_allclose(rm, expect, rtol=1e-4)


def test_cond():
    x = paddle.static.data("x", [], "float32")
    out = paddle.static.nn.cond(x > 0.0,
                                lambda: x * 2.0,
                                lambda: x - 1.0)
    exe = _exe()
    (a,) = exe.run(feed={"x": np.float32(3.0)}, fetch_list=[out])
    (b,) = exe.run(feed={"x": np.float32(-3.0)}, fetch_list=[out])
    assert a == 6.0 and b == -4.0


def test_while_loop():
    i = paddle.static.data("i", [], "int64")
    s = paddle.static.data("s", [], "float32")
    iv, sv = paddle.static.nn.while_loop(
        lambda i, s: i < 5,
        lambda i, s: [i + 1, s * 2.0],
        [i, s])
    exe = _exe()
    outs = exe.run(feed={"i": np.int64(0), "s": np.float32(1.0)},
                   fetch_list=[iv, sv])
    assert outs[0] == 5 and outs[1] == 32.0


def test_static_save_load():
    x = paddle.static.data("x", [-1, 3], "float32")
    lin = paddle.nn.Linear(3, 2)
    out = lin(x)
    prog = paddle.static.default_main_program()
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    scope = paddle.static.global_scope()
    orig = np.asarray(scope.find_var(lin.weight.name))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        paddle.static.save(prog, path)
        scope.set(lin.weight.name, orig * 0)
        paddle.static.load(prog, path)
        now = np.asarray(scope.find_var(lin.weight.name))
        np.testing.assert_allclose(now, orig)


def test_save_load_inference_model():
    x = paddle.static.data("x", [-1, 4], "float32")
    lin = paddle.nn.Linear(4, 2)
    out = paddle.nn.functional.softmax(lin(x))
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    xs = np.random.randn(3, 4).astype(np.float32)
    (want,) = exe.run(feed={"x": xs}, fetch_list=[out])
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "infer")
        paddle.static.save_inference_model(prefix, [x], [out], exe)
        prog, feeds, fetches = paddle.static.load_inference_model(prefix)
        got = prog(xs)[0]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_program_guard_isolation():
    outer = paddle.static.default_main_program()
    p = paddle.static.Program()
    s = paddle.static.Program()
    with paddle.static.program_guard(p, s):
        x = paddle.static.data("x", [2], "float32")
        _ = x + 1.0
        assert paddle.static.default_main_program() is p
    assert paddle.static.default_main_program() is outer
    assert len(p.ops) == 1


def test_clone_for_test_strips_training_tail():
    x = paddle.static.data("x", [4, 2], "float32")
    lin = paddle.nn.Linear(2, 1)
    loss = lin(x).mean()
    n_fwd = len(paddle.static.default_main_program().ops)
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = paddle.static.default_main_program()
    test_prog = prog.clone(for_test=True)
    assert len(prog.ops) > n_fwd
    assert len(test_prog.ops) == n_fwd
    # eval program runs without touching params
    exe = _exe()
    exe.run(paddle.static.default_startup_program())
    scope = paddle.static.global_scope()
    before = np.asarray(scope.find_var(lin.weight.name))
    exe.run(test_prog, feed={"x": np.ones((4, 2), np.float32)},
            fetch_list=[test_prog.global_block.var(loss.name)])
    after = np.asarray(scope.find_var(lin.weight.name))
    np.testing.assert_allclose(before, after)


def test_gradients_api():
    x = paddle.static.data("x", [3], "float32")
    y = (x ** 2).sum()
    (gx,) = paddle.static.gradients(y, x)
    exe = _exe()
    xs = np.array([1.0, 2.0, 3.0], np.float32)
    outs = exe.run(feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(outs[0], 2 * xs)


# ------------------------------------------------- round-3 completeness
def test_gradients_multiple_and_nonscalar_targets():
    """reference backward.py:1795 calc_gradient: multiple targets and
    explicit target_gradients."""
    static = paddle.static
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [3, 4], "float32")
        w = static.create_global_var([4, 2], 0.5, "float32", name="w",
                                     persistable=True)
        y1 = paddle.matmul(x, w)              # non-scalar target
        y2 = (x ** 2).sum()                   # scalar target
        tg = static.data("tg", [3, 2], "float32")
        g_tg = static.gradients([y1], [x], target_gradients=[tg])
        g_multi = static.gradients([y1, y2], [x])
    exe = static.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    tgv = np.random.RandomState(1).randn(3, 2).astype(np.float32)
    outs = exe.run(main, feed={"x": xv, "tg": tgv},
                   fetch_list=[g_tg[0], g_multi[0]])
    wv = np.full((4, 2), 0.5, np.float32)
    np.testing.assert_allclose(outs[0], tgv @ wv.T, rtol=1e-4)
    np.testing.assert_allclose(
        outs[1], np.ones((3, 2), np.float32) @ wv.T + 2 * xv,
        rtol=1e-4)


def test_static_amp_lenet_converges():
    """reference fp16_utils.py:468 rewrite_program + decorator.py:415:
    bf16 compute, fp32 masters, dynamic loss scaling — LeNet-class conv
    net must converge on a separable task."""
    static = paddle.static
    main = static.Program()
    startup = static.Program()
    rs = np.random.RandomState(0)
    with static.program_guard(main, startup):
        x = static.data("x", [32, 1, 12, 12], "float32")
        y = static.data("y", [32, 1], "int64")
        h = static.nn.conv2d(x, 6, 3, act="relu")
        net = static.nn.fc(h, 10)
        loss = paddle.nn.functional.cross_entropy(net, y)
        opt = paddle.optimizer.Momentum(0.05)
        mp = static.amp.decorate(opt, init_loss_scaling=1024.0)
        mp.minimize(loss)
    # the rewritten program really runs white-listed ops in bf16
    types = [od.op_type for od in main.ops]
    assert "conv2d" in types and "backward" in types
    exe = static.Executor()
    exe.run(startup)
    losses = []
    for i in range(15):
        xv = rs.randn(32, 1, 12, 12).astype(np.float32)
        yv = ((xv.mean(axis=(1, 2, 3)) > 0) * 3).astype(
            np.int64).reshape(-1, 1)
        out = exe.run(main, feed={"x": xv, "y": yv},
                      fetch_list=[loss, mp.get_loss_scaling()])
        losses.append(float(out[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert float(out[1]) > 0  # scale alive


def test_program_persistence_roundtrip(tmp_path):
    """reference fluid/io.py:621 + program_desc.cc: save a recorded
    Program + persistables, rebuild from code, load, training continues
    bit-identically; structural mismatch is rejected."""
    from paddle_tpu.static.io import save_program, load_program
    static = paddle.static

    def build():
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [8, 4], "float32")
            y = static.data("y", [8, 1], "float32")
            h = static.nn.fc(x, 8, activation="tanh")
            out = static.nn.fc(h, 1)
            loss = ((out - y) ** 2).mean()
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    main, startup, loss = build()
    exe = static.Executor()
    exe.run(startup)
    rs = np.random.RandomState(0)
    xv = rs.randn(8, 4).astype(np.float32)
    yv = rs.randn(8, 1).astype(np.float32)
    for _ in range(3):
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    save_program(main, str(tmp_path / "model"))
    expected = exe.run(main, feed={"x": xv, "y": yv},
                       fetch_list=[loss])[0]

    static.global_scope().drop_kids()
    paddle.utils.unique_name.switch()
    main2, startup2, loss2 = build()
    load_program(main2, str(tmp_path / "model"))
    resumed = exe.run(main2, feed={"x": xv, "y": yv},
                      fetch_list=[loss2])[0]
    np.testing.assert_allclose(resumed, expected, rtol=1e-6)

    # different model code → loud structural rejection
    main3 = static.Program()
    startup3 = static.Program()
    with static.program_guard(main3, startup3):
        x = static.data("x", [8, 4], "float32")
        static.nn.fc(x, 2)
    with pytest.raises(ValueError):
        load_program(main3, str(tmp_path / "model"))
