"""Custom-op toolchain: utils.cpp_extension.load -> ctypes -> framework op.

Reference: python/paddle/utils/cpp_extension/ builds pybind11 custom ops;
the TPU-native path is g++ -shared + ctypes + py_func/pure_callback (the
same pattern the in-tree native datafeed/crypto use)."""
import ctypes
import os
import shutil
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.extra_ops import py_func
from paddle_tpu.utils import cpp_extension

SRC = textwrap.dedent("""
    extern "C" void scaled_add_one(const float* x, float* out, long n,
                                   float scale) {
        for (long i = 0; i < n; ++i) out[i] = x[i] * scale + 1.0f;
    }
""")


@pytest.fixture(scope="module")
def lib(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    d = tmp_path_factory.mktemp("ext")
    src = d / "custom.cc"
    src.write_text(SRC)
    return cpp_extension.load("custom_ext", [str(src)],
                              build_directory=str(d))


def test_load_builds_and_calls(lib):
    fn = lib.scaled_add_one
    fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                   ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                   ctypes.c_float]
    x = np.arange(4, dtype=np.float32)
    out = np.empty_like(x)
    fn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 4, 2.0)
    np.testing.assert_allclose(out, x * 2.0 + 1.0)


def test_custom_op_through_py_func_eager_and_jit(lib):
    """The documented custom-op flow: wrap the native symbol as a host
    callable and run it as a framework op — eagerly and inside a jitted
    function via pure_callback."""
    fn = lib.scaled_add_one
    fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                   ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                   ctypes.c_float]

    def host_op(a):
        a = np.ascontiguousarray(a, dtype=np.float32)
        out = np.empty_like(a)
        fn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           a.size, 3.0)
        return out.reshape(a.shape)

    x_np = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    x = paddle.to_tensor(x_np)
    # eager
    out = py_func(host_op, x)
    np.testing.assert_allclose(out.numpy(), x_np * 3.0 + 1.0, rtol=1e-6)
    # jit (pure_callback lowering)
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(v):
        t = py_func(host_op, paddle.Tensor(v),
                    out_template=paddle.to_tensor(x_np))
        return t._value + jnp.float32(1.0)

    np.testing.assert_allclose(np.asarray(f(x._value)),
                               x_np * 3.0 + 2.0, rtol=1e-6)


def test_cuda_extension_loud_fail():
    with pytest.raises(NotImplementedError):
        cpp_extension.CUDAExtension(["a.cu"])
