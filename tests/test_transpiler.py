"""DistributeTranspiler (legacy PS transpile API) over the modern ps
runtime (reference: fluid/transpiler/distribute_transpiler.py:1)."""
import socket

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed import DistributeTranspiler


@pytest.fixture(autouse=True)
def _static_mode():
    static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        yield
        paddle.disable_static()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _build_batch(bs):
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [bs, 4], "float32")
        y = static.data("y", [bs, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = ((pred - y) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _build():
    return _build_batch(16)


def test_transpiled_training_matches_local_sgd():
    """Trainer+pserver split must reproduce the local program's loss
    sequence step for step (server-side SGD == the stripped update)."""
    w_true = np.array([[1.], [2.], [-1.], [0.5]], np.float32)
    rs = np.random.RandomState(0)
    data = [(xv, xv @ w_true) for xv in
            (rs.randn(16, 4).astype(np.float32) for _ in range(10))]

    paddle.seed(11)
    main, startup, loss = _build()
    exe = static.Executor()
    exe.run(startup)
    local = [float(exe.run(main, feed={"x": xv, "y": yv},
                           fetch_list=[loss])[0]) for xv, yv in data]

    static.global_scope().drop_kids()
    paddle.seed(11)
    main2, startup2, loss2 = _build()
    exe2 = static.Executor()
    exe2.run(startup2)
    eps = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main2, pservers=",".join(eps),
                trainers=1)
    servers = [t.get_pserver_program(ep) for ep in eps]
    for s in servers:
        s.serve(block=False)  # in-thread for the test
    try:
        tp = t.get_trainer_program()
        dist = [float(exe2.run(tp, feed={"x": xv, "y": yv},
                               fetch_list=[loss2])[0]) for xv, yv in data]
        np.testing.assert_allclose(dist, local, rtol=1e-4)
        assert dist[-1] < dist[0] * 0.25
    finally:
        for s in servers:
            s.stop()


def test_two_trainer_sync_matches_big_batch_sgd():
    """Sync mode with 2 trainers: each pushes grad/2, so the combined
    pserver update is lr*mean over both half-batches == local SGD on the
    full batch (reference: transpiler inserts scale 1/trainer_num,
    distribute_transpiler.py:2237). Covers the multi-trainer scaling path
    tests previously left silent."""
    import threading

    w_true = np.array([[1.], [2.], [-1.], [0.5]], np.float32)
    rs = np.random.RandomState(7)
    steps = 10
    # per step: two half-batches of 16 (trainer 0 and trainer 1)
    halves = [[rs.randn(16, 4).astype(np.float32) for _ in range(2)]
              for _ in range(steps)]

    # local oracle: SGD on the concatenated 32-row batch (MSE mean over 32
    # == mean of the two half-batch means)
    paddle.seed(11)
    main, startup, loss = _build_batch(32)
    exe = static.Executor()
    exe.run(startup)
    local = []
    for h0, h1 in halves:
        xv = np.concatenate([h0, h1], 0)
        local.append(float(exe.run(main, feed={"x": xv, "y": xv @ w_true},
                                   fetch_list=[loss])[0]))

    eps = [f"127.0.0.1:{_free_port()}"]
    results, errors = {}, []

    # build both trainer sides serially (program construction uses global
    # default-program state — threads only RUN the step loop)
    from paddle_tpu.static.executor import Scope
    static.global_scope().drop_kids()
    rigs = []
    for tid in range(2):
        paddle.seed(11)
        with paddle.utils.unique_name.guard():
            m, su, ls = _build_batch(16)
        scope = Scope()
        e = static.Executor()
        e.run(su, scope=scope)
        t = DistributeTranspiler()
        t.transpile(trainer_id=tid, program=m, pservers=",".join(eps),
                    trainers=2, sync_mode=True)
        rigs.append((e, t.get_trainer_program(), ls, scope))

    def trainer(tid):
        try:
            e, tp, ls, scope = rigs[tid]
            out = []
            for step in range(steps):
                xv = halves[step][tid]
                out.append(float(e.run(tp, feed={"x": xv, "y": xv @ w_true},
                                       fetch_list=[ls], scope=scope)[0]))
            results[tid] = out
        except Exception as exc:  # surface thread failures in the test
            errors.append(exc)

    paddle.seed(11)
    with paddle.utils.unique_name.guard():
        main2, _su2, _ls2 = _build_batch(16)
    t0 = DistributeTranspiler()
    t0.transpile(trainer_id=0, program=main2, pservers=",".join(eps),
                 trainers=2, sync_mode=True)
    server = t0.get_pserver_program(eps[0])
    server.serve(block=False)
    try:
        ths = [threading.Thread(target=trainer, args=(tid,))
               for tid in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120)
        assert not errors, errors
        assert set(results) == {0, 1}
        # both trainers observed the same parameter trajectory; per-step
        # loss on a half-batch differs from the 32-row oracle only through
        # which half it is evaluated on, so check the shared-parameter
        # consequence: mean of the two half-batch losses == full-batch loss
        merged = [0.5 * (results[0][i] + results[1][i])
                  for i in range(steps)]
        np.testing.assert_allclose(merged, local, rtol=1e-3)
        assert merged[-1] < merged[0] * 0.5
    finally:
        server.stop()


def test_transpile_requires_backward():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 2], "float32")
        static.nn.fc(x, 1)
    with pytest.raises(ValueError):
        DistributeTranspiler().transpile(0, program=main,
                                         pservers="127.0.0.1:1")


def test_fleet_v1_compat_namespace():
    """incubate.fleet (fleet v1, reference incubate/fleet/base/
    fleet_base.py) delegates to fleet 2.0: init/topology/
    distributed_optimizer keep the v1 meanings."""
    from paddle_tpu.incubate.fleet import fleet
    paddle.disable_static()
    fleet.init(is_collective=True)
    assert fleet.is_worker() and not fleet.is_server()
    assert fleet.worker_num() >= 1 and fleet.worker_index() == 0
    assert fleet.is_first_worker()
    assert isinstance(fleet.worker_endpoints(to_string=True), str)

    m = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    dopt = fleet.distributed_optimizer(opt)
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(8, 2).astype(np.float32))
    l0 = float((((m(x) - y) ** 2).mean()).numpy())
    for _ in range(5):
        loss = ((m(x) - y) ** 2).mean()
        dopt.minimize(loss)
        m.clear_gradients()
    assert float(loss.numpy()) < l0
