"""DistributeTranspiler (legacy PS transpile API) over the modern ps
runtime (reference: fluid/transpiler/distribute_transpiler.py:1)."""
import socket

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed import DistributeTranspiler


@pytest.fixture(autouse=True)
def _static_mode():
    static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        yield
        paddle.disable_static()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _build():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [16, 4], "float32")
        y = static.data("y", [16, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = ((pred - y) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def test_transpiled_training_matches_local_sgd():
    """Trainer+pserver split must reproduce the local program's loss
    sequence step for step (server-side SGD == the stripped update)."""
    w_true = np.array([[1.], [2.], [-1.], [0.5]], np.float32)
    rs = np.random.RandomState(0)
    data = [(xv, xv @ w_true) for xv in
            (rs.randn(16, 4).astype(np.float32) for _ in range(10))]

    paddle.seed(11)
    main, startup, loss = _build()
    exe = static.Executor()
    exe.run(startup)
    local = [float(exe.run(main, feed={"x": xv, "y": yv},
                           fetch_list=[loss])[0]) for xv, yv in data]

    static.global_scope().drop_kids()
    paddle.seed(11)
    main2, startup2, loss2 = _build()
    exe2 = static.Executor()
    exe2.run(startup2)
    eps = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main2, pservers=",".join(eps),
                trainers=1)
    servers = [t.get_pserver_program(ep) for ep in eps]
    for s in servers:
        s.serve(block=False)  # in-thread for the test
    try:
        tp = t.get_trainer_program()
        dist = [float(exe2.run(tp, feed={"x": xv, "y": yv},
                               fetch_list=[loss2])[0]) for xv, yv in data]
        np.testing.assert_allclose(dist, local, rtol=1e-4)
        assert dist[-1] < dist[0] * 0.25
    finally:
        for s in servers:
            s.stop()


def test_transpile_requires_backward():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 2], "float32")
        static.nn.fc(x, 1)
    with pytest.raises(ValueError):
        DistributeTranspiler().transpile(0, program=main,
                                         pservers="127.0.0.1:1")


def test_fleet_v1_compat_namespace():
    """incubate.fleet (fleet v1, reference incubate/fleet/base/
    fleet_base.py) delegates to fleet 2.0: init/topology/
    distributed_optimizer keep the v1 meanings."""
    from paddle_tpu.incubate.fleet import fleet
    paddle.disable_static()
    fleet.init(is_collective=True)
    assert fleet.is_worker() and not fleet.is_server()
    assert fleet.worker_num() >= 1 and fleet.worker_index() == 0
    assert fleet.is_first_worker()
    assert isinstance(fleet.worker_endpoints(to_string=True), str)

    m = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    dopt = fleet.distributed_optimizer(opt)
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(8, 2).astype(np.float32))
    l0 = float((((m(x) - y) ** 2).mean()).numpy())
    for _ in range(5):
        loss = ((m(x) - y) ** 2).mean()
        dopt.minimize(loss)
        m.clear_gradients()
    assert float(loss.numpy()) < l0
