"""Mixture-of-Experts + expert parallelism ('ep' mesh axis).

Reference context: the 2.0/2.1-dev snapshot scales sparse capacity via the
PS distributed lookup table (distribute_transpiler.py:393); MoE landed in
later paddle (incubate.distributed.models.moe) on the same
dispatch/combine design. These tests validate the TPU-native
expert-parallel layer (distributed/moe.py): routing math against a dense
oracle, capacity-overflow semantics, load-balance aux, and n-device loss
parity in the TestDistBase style (test_dist_base.py:660 — same model,
same data, sharded run must match the 1-device run).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.moe import MoEMLP, moe_dispatch_combine
from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
from paddle_tpu.parallel import build_mesh, set_global_mesh, ShardedTrainStep


@pytest.fixture(autouse=True)
def _clear_mesh():
    set_global_mesh(None)
    yield
    set_global_mesh(None)


def _gelu(h):
    return 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                  * (h + 0.044715 * h ** 3)))


def _force_router(m, expert):
    """Router logits that send every token to `expert` with gate ~1."""
    r = np.full((m.router.shape[0], m.num_experts), -20.0, np.float32)
    r[:, expert] = 20.0
    # constant over the feature dim: logits = sum(x) * row — instead make
    # the router ignore x by zeroing weight and using the softmax of a
    # fixed bias folded into one input row; simpler: set every row equal
    # so logits = (sum_h x_h) * bias_pattern. Sign of sum(x) could flip
    # the argmax, so route through a weight that yields the pattern for
    # any x: not expressible with a linear router alone. Use x >= 0 data
    # in the callers instead.
    m.router._value = jnp.asarray(r / m.router.shape[0])


def test_moe_forced_routing_matches_dense_expert():
    paddle.seed(0)
    m = MoEMLP(16, num_experts=4, ffn_hidden_size=32, top_k=1,
               capacity_factor=8.0)
    _force_router(m, 1)
    x = np.abs(np.random.RandomState(0).randn(1, 6, 16)).astype(np.float32)
    out = m(paddle.to_tensor(x)).numpy().reshape(6, 16)
    h = x.reshape(6, 16) @ m.w_up.numpy()[1] + m.b_up.numpy()[1]
    dense = _gelu(h) @ m.w_down.numpy()[1] + m.b_down.numpy()[1]
    # gate = softmax gap of 40 logits ≈ 1.0
    np.testing.assert_allclose(out, dense, rtol=1e-4, atol=1e-4)
    assert float(m.aux_loss.numpy()) > 1.5  # maximally unbalanced > 1


def test_moe_top2_renormalised_combine():
    # uniform router -> every token takes two experts at gate 0.5 each;
    # output must be the MEAN of the two dense expert FFNs (GShard top-2
    # normalisation), not the raw 0.25+0.25 softmax mass.
    paddle.seed(1)
    m = MoEMLP(8, num_experts=2, ffn_hidden_size=16, top_k=2,
               capacity_factor=8.0)
    m.router._value = jnp.zeros((8, 2), jnp.float32)
    x = np.random.RandomState(1).randn(1, 5, 8).astype(np.float32)
    out = m(paddle.to_tensor(x)).numpy().reshape(5, 8)
    xs = x.reshape(5, 8)
    dense = []
    for e in (0, 1):
        h = xs @ m.w_up.numpy()[e] + m.b_up.numpy()[e]
        dense.append(_gelu(h) @ m.w_down.numpy()[e] + m.b_down.numpy()[e])
    np.testing.assert_allclose(out, 0.5 * (dense[0] + dense[1]),
                               rtol=1e-4, atol=1e-4)
    # perfectly balanced -> aux == E * sum(1/E * 1/E * E) == 1
    np.testing.assert_allclose(float(m.aux_loss.numpy()), 1.0, atol=1e-4)


def test_moe_capacity_overflow_drops_to_zero():
    # all 8 tokens routed to expert 0 with capacity 1: token 0 is served,
    # tokens 1..7 dropped -> expert-path output exactly 0 (the residual
    # carries them in a transformer block; Switch semantics)
    paddle.seed(2)
    m = MoEMLP(8, num_experts=4, ffn_hidden_size=16, top_k=1,
               capacity_factor=0.25, min_capacity=1)
    _force_router(m, 0)
    x = np.abs(np.random.RandomState(2).randn(1, 8, 8)).astype(np.float32)
    out = m(paddle.to_tensor(x)).numpy().reshape(8, 8)
    assert np.abs(out[0]).sum() > 0
    np.testing.assert_array_equal(out[1:], np.zeros_like(out[1:]))


def test_moe_dispatch_combine_positions():
    # 4 tokens, 2 experts, alternating routing: per-expert queue positions
    # must be 0,1 (not global token index)
    gates = jnp.asarray([[0.9, 0.1], [0.1, 0.9], [0.9, 0.1], [0.1, 0.9]],
                        jnp.float32)
    disp, comb, aux = moe_dispatch_combine(gates, top_k=1, capacity=2)
    d = np.asarray(disp)
    assert d[0, 0, 0] == 1 and d[2, 0, 1] == 1    # expert 0 queue
    assert d[1, 1, 0] == 1 and d[3, 1, 1] == 1    # expert 1 queue
    assert d.sum() == 4
    np.testing.assert_allclose(np.asarray(comb).sum(axis=(1, 2)),
                               [0.9, 0.9, 0.9, 0.9], rtol=1e-6)


def test_moe_grads_flow_to_all_experts():
    paddle.seed(3)
    m = MoEMLP(8, num_experts=2, ffn_hidden_size=16, top_k=2,
               capacity_factor=4.0)
    m.router._value = jnp.zeros((8, 2), jnp.float32)
    x = paddle.to_tensor(
        np.random.RandomState(3).randn(2, 4, 8).astype(np.float32))
    (m(x).sum() + m.aux_loss).backward()
    for p in (m.router, m.w_up, m.b_up, m.w_down, m.b_down):
        assert p.grad is not None
        assert np.abs(p.grad.numpy()).sum() > 0
    g = m.w_up.grad.numpy()
    assert np.abs(g[0]).sum() > 0 and np.abs(g[1]).sum() > 0


def _run_moe_gpt(mesh_kw, steps=5, **cfg_kw):
    paddle.seed(0)
    mesh = build_mesh(**mesh_kw)
    set_global_mesh(mesh)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=16, moe_experts=4,
                    moe_top_k=2, moe_every=1, moe_capacity_factor=2.0,
                    **cfg_kw)
    model = GPT(cfg)
    optim = opt.Adam(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(0, 64, (8, 16)))
    y = paddle.to_tensor(rng.randint(0, 64, (8, 16)))
    return [float(step(x, y).numpy()) for _ in range(steps)], step


def test_moe_ep_parity_vs_single_device():
    base, _ = _run_moe_gpt(dict(dp=1, devices=jax.devices()[:1]))
    ep, _ = _run_moe_gpt(dict(dp=2, ep=4))
    np.testing.assert_allclose(base, ep, rtol=2e-3, atol=2e-3)
    assert base[-1] < base[0]  # it actually trains


def test_moe_ep_recompute_parity():
    # aux loss must survive the checkpointed block (rides the recompute
    # return, models/gpt.py GPTBlock.forward)
    base, _ = _run_moe_gpt(dict(dp=1, devices=jax.devices()[:1]),
                           use_recompute=True)
    dense_base, _ = _run_moe_gpt(dict(dp=1, devices=jax.devices()[:1]))
    # recompute changes no math
    np.testing.assert_allclose(base, dense_base, rtol=2e-3, atol=2e-3)
    ep, _ = _run_moe_gpt(dict(dp=2, ep=4), use_recompute=True)
    np.testing.assert_allclose(base, ep, rtol=2e-3, atol=2e-3)


def test_moe_ep_hlo_has_all_to_all():
    # the compile-time strategy assertion (analogue of the reference's
    # meta-optimizer ProgramDesc greps, test_fleet_sharding_meta_optimizer):
    # dp-sharded tokens x ep-sharded experts must move via collectives on
    # the ep axis — GSPMD emits all-to-all (or all-gather+dyn-slice on
    # some geometries); assert the expert boundary produced SOME ep
    # collective beyond plain dp all-reduce
    _, step = _run_moe_gpt(dict(dp=2, ep=4), steps=1)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(0, 64, (8, 16)))
    y = paddle.to_tensor(rng.randint(0, 64, (8, 16)))
    hlo = step.compiled_text(x, y)
    # the assertion must not be satisfiable by dp-only collectives (size-2
    # groups for grad allreduce): require a boundary collective whose
    # replica groups span >= the ep degree (4), i.e. devices that differ
    # along the ep axis actually exchange data
    import re
    sizes = set()
    for line in hlo.splitlines():
        if not re.search(r"all-to-all|all-gather|collective-permute", line):
            continue
        m = re.search(r"replica_groups=\{(.*?)\}\}", line)
        if m:
            sizes |= {len(g.split(","))
                      for g in re.findall(r"\{([0-9,]+)\}", m.group(1) + "}")}
        m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", line)
        if m:  # iota form [G,S]<=[N]: G groups of S
            sizes.add(int(m.group(2)))
    assert any(s >= 4 for s in sizes), \
        f"no collective spanning the ep axis (group sizes seen: {sizes})"
