"""jaxnum: the whole-program numerics analyzer and its committed plan.

Covers the ISSUE-20 contract:
  - dtype-lattice predicates (the one lattice jaxpr_audit delegates to),
  - bound exactness: hand-computed matmul-chain error in f32 ulps,
    f32 vs bf16 storage vs bf16 accumulation (NUM-ACC TP and TN),
  - scan error growth with trip count (exact iteration + linear tail
    extrapolation past SCAN_EXACT_MAX),
  - NUM-FINITE true positive AND clamp-provenance true negative for
    exp and div,
  - NUM-CAST: lossy roundtrip detection, integer narrowing with
    range-proven (iota / clamp) true negatives,
  - int8 KV codec: derived bound == declared budget, and SOUNDNESS —
    the static bound dominates the measured max dequant error while
    staying within 4x of it (no vacuous over-bound),
  - registry/plan coverage in both directions, every committed finding
    suppressed with a reason,
  - diff_plans structural + tolerance drift detection,
  - CLI exit-code semantics (0 clean / 1 violation / 2 usage),
  - quant_ops regression pins (zero-point tie parity, window-restart
    divisor guard),
  - jaxpr_audit "int_narrowing" stays opt-in (outside DEFAULT_CHECKS).
"""
import copy
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import jaxnum, jaxpr_audit

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
JAXNUM_CLI = REPO / "tools" / "jaxnum.py"
PLAN_FILE = REPO / "numplan.json"

BF16 = 2 ** 16          # ulps32 of one bfloat16 rounding (23-7 bits)
F16 = 2 ** 13           # ulps32 of one float16 rounding (23-10 bits)


# -------------------------------------------------------------- lattice
class TestLattice:
    def test_float_downcast_predicate(self):
        f64, f32 = np.dtype(np.float64), np.dtype(np.float32)
        f16, bf16 = np.dtype(np.float16), np.dtype(jnp.bfloat16)
        assert jaxnum.lossy_float_downcast(f32, f16)
        assert jaxnum.lossy_float_downcast(f32, bf16)
        assert jaxnum.lossy_float_downcast(f64, f16)
        # x64 mode makes f64 inputs routine; f64 -> f32 is the
        # deliberate repo-wide normalization, not a lossy event
        assert not jaxnum.lossy_float_downcast(f64, f32)
        assert not jaxnum.lossy_float_downcast(f16, f32)   # widening
        assert not jaxnum.lossy_float_downcast(f16, bf16)  # already sub-32

    def test_int_narrowing_predicate(self):
        i64, i32, i8 = (np.dtype(np.int64), np.dtype(np.int32),
                        np.dtype(np.int8))
        assert jaxnum.lossy_int_narrowing(i64, i32)
        assert jaxnum.lossy_int_narrowing(i32, i8)
        assert not jaxnum.lossy_int_narrowing(i32, i64)
        assert not jaxnum.lossy_int_narrowing(i32, np.dtype(np.float32))

    def test_ulps32_scale(self):
        assert jaxnum.ulps32(np.dtype(np.float32)) == 1.0
        assert jaxnum.ulps32(np.dtype(jnp.bfloat16)) == BF16
        assert jaxnum.ulps32(np.dtype(np.float16)) == F16
        # f64 rounding is far below one f32 ulp
        assert jaxnum.ulps32(np.dtype(np.float64)) < 1e-8

    def test_opaque_dtypes_tolerated(self):
        key = jax.random.key(0)
        # extended dtypes (PRNG keys) must pass through the lattice
        # without np.dtype explosions
        assert not jaxnum.is_float(jaxnum._dt(key.dtype))
        assert not jaxnum.is_int(jaxnum._dt(key.dtype))


# ------------------------------------------------------- bound exactness
class TestBounds:
    def test_matmul_chain_hand_computed(self):
        """(a @ b) @ c, all f32: each dot charges n * u(acc) + u(out)
        = K + 1 ulps on top of the operand errors.
        a[8,64] @ b[64,16]: 64 + 1 = 65; @ c[16,4]: 65 + 16 + 1 = 82."""
        a = jnp.zeros((8, 64), jnp.float32)
        b = jnp.zeros((64, 16), jnp.float32)
        c = jnp.zeros((16, 4), jnp.float32)
        rep = jaxnum.analyze_fn(lambda a, b, c: (a @ b) @ c,
                                a, b, c, name="t.chain")
        assert rep.max_error_ulps == 82.0
        assert rep.findings == []
        assert rep.acc_dtypes == ["float32"]

    def test_bf16_storage_f32_accum(self):
        """bf16 storage casts cost 2^16 ulps each; the f32-accumulated
        dot adds 64 + 1: 2*65536 + 65 = 131137. No NUM-ACC — the
        accumulator is full-width."""
        a = jnp.zeros((8, 64), jnp.float32)
        b = jnp.zeros((64, 16), jnp.float32)

        def f(a, b):
            return jnp.dot(a.astype(jnp.bfloat16),
                           b.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)

        rep = jaxnum.analyze_fn(f, a, b, name="t.chain16")
        assert rep.max_error_ulps == 2 * BF16 + 65
        assert not [f for f in rep.findings if f.rule == "NUM-ACC"]
        assert rep.acc_dtypes == ["float32"]

    def test_bf16_accumulation_num_acc(self):
        """Accumulating IN bf16 multiplies the n-term by 2^16:
        65536 * (2 + 64 + 1) — and NUM-ACC must fire (u(acc) > 1,
        n = 64 >= NUM_ACC_MIN_ELEMS)."""
        a = jnp.zeros((8, 64), jnp.float32)
        b = jnp.zeros((64, 16), jnp.float32)

        def f(a, b):
            return jax.lax.dot_general(
                a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.bfloat16)

        rep = jaxnum.analyze_fn(f, a, b, name="t.acc16")
        assert rep.max_error_ulps == BF16 * 67
        keys = [f.key for f in rep.findings]
        assert "acc:dot_general:bfloat16" in keys

    def test_scan_error_grows_with_trip_count(self):
        """An eps-accumulating carry grows linearly in T — exactly
        iterated to SCAN_EXACT_MAX, linear tail extrapolation past it
        (T=512 > 256 must note the extrapolation and keep the slope)."""
        x = jnp.zeros((4,), jnp.float32)

        def make(T):
            def f(x):
                def body(c, _):
                    c = c * 1.000001 + 1.0
                    return c, c
                out, _ = jax.lax.scan(body, x, None, length=T)
                return out
            return f

        e32 = jaxnum.analyze_fn(make(32), x, name="t.s32")
        e128 = jaxnum.analyze_fn(make(128), x, name="t.s128")
        e512 = jaxnum.analyze_fn(make(512), x, name="t.s512")
        assert e32.max_error_ulps == 64.0      # 2 ulps per trip
        assert e128.max_error_ulps == 256.0
        assert e512.max_error_ulps == 1024.0   # extrapolated tail
        assert any("extrapolat" in n for n in e512.notes)


# ------------------------------------------------------------ NUM-FINITE
class TestFinite:
    X = jnp.zeros((4,), jnp.float32)

    def test_exp_unbounded_fires(self):
        rep = jaxnum.analyze_fn(lambda x: jnp.exp(x), self.X, name="t.e")
        assert "finite:exp" in [f.key for f in rep.findings]

    def test_exp_clamped_is_clean(self):
        rep = jaxnum.analyze_fn(
            lambda x: jnp.exp(jnp.clip(x, -10.0, 10.0)), self.X,
            name="t.ec")
        assert rep.findings == []

    def test_div_unbounded_denominator_fires(self):
        rep = jaxnum.analyze_fn(lambda x, y: x / y, self.X, self.X,
                                name="t.d")
        assert "finite:div:div" in [f.key for f in rep.findings]

    def test_div_clamped_denominator_is_clean(self):
        rep = jaxnum.analyze_fn(
            lambda x, y: x / jnp.clip(y, 1.0, 2.0), self.X, self.X,
            name="t.dc")
        assert rep.findings == []


# -------------------------------------------------------------- NUM-CAST
class TestCast:
    def test_lossy_roundtrip_detected(self):
        x = jnp.zeros((4,), jnp.float32)
        rep = jaxnum.analyze_fn(
            lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), x,
            name="t.rt")
        assert [f.key for f in rep.findings] == \
            ["cast:roundtrip:bfloat16->float32"]
        # the widening cannot recover the 2^16-ulp storage loss
        assert rep.max_error_ulps == BF16

    def test_int_narrowing_unproven_fires(self):
        x = jnp.zeros((4,), jnp.int64)
        rep = jaxnum.analyze_fn(lambda x: x.astype(jnp.int32), x,
                                name="t.n")
        assert "cast:int:int64->int32" in [f.key for f in rep.findings]

    def test_int_narrowing_proven_range_is_clean(self):
        """Interval provenance refutes the narrowing: iota and clamp
        both prove the value fits int32 — the range-aware gate that
        jaxpr_audit's blanket opt-in check can't provide."""
        x = jnp.zeros((4,), jnp.int64)
        r1 = jaxnum.analyze_fn(
            lambda: jnp.arange(10, dtype=jnp.int64).astype(jnp.int32),
            name="t.ni")
        r2 = jaxnum.analyze_fn(
            lambda x: jnp.clip(x, 0, 100).astype(jnp.int32), x,
            name="t.nc")
        assert r1.findings == []
        assert r2.findings == []


# ----------------------------------------------------------- int8 codec
class TestCodec:
    def test_derived_bound_matches_budget(self):
        from paddle_tpu.inference.serving import kv_quant
        x = jnp.zeros((4, 16, 4, 8), jnp.float32)
        rep = jaxnum.analyze_fn(
            kv_quant.kv_block_roundtrip, x, name="t.codec",
            suppress={"finite:div:div": "where-guarded"},
            quant_budget=kv_quant.KV_INT8_REL_ERR)
        assert rep.quant is not None
        assert rep.quant["levels"] == kv_quant.KV_INT8_LEVELS
        assert rep.quant["derived_rel_err"] == \
            pytest.approx(0.5 / kv_quant.KV_INT8_LEVELS, rel=1e-4)
        assert rep.unsuppressed() == []

    def test_static_bound_sound_and_tight(self):
        """The committed bound must DOMINATE the measured max dequant
        error (soundness) without being vacuous (<= 4x measured)."""
        from paddle_tpu.inference.serving import kv_quant
        bound = jaxnum.committed_codec_bound(str(PLAN_FILE))
        assert bound is not None
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(8, 16, 4, 16).astype(np.float32))
        xhat = kv_quant.kv_block_roundtrip(x)
        absmax = jnp.max(jnp.abs(x), axis=(1, 3), keepdims=True)
        measured = float(jnp.max(jnp.abs(x - xhat) / absmax))
        assert measured <= bound * (1 + 1e-6)
        assert bound <= 4 * measured

    def test_undeclared_budget_fires(self):
        from paddle_tpu.inference.serving import kv_quant
        x = jnp.zeros((2, 4, 2, 4), jnp.float32)
        rep = jaxnum.analyze_fn(kv_quant.kv_block_roundtrip, x,
                                name="t.nb")
        assert "quant:undeclared" in [f.key for f in rep.findings]


# ------------------------------------------------------ registry / plan
@pytest.fixture(scope="module")
def reports():
    return jaxnum.compute_reports()


class TestPlan:
    def test_registry_coverage_both_directions(self, reports):
        names = set(jaxnum.registry_names())
        assert len(names) >= 12
        assert set(reports) == names
        plan = jaxnum.load_plan(str(PLAN_FILE))
        assert plan is not None, "numplan.json must be committed"
        assert set(plan["programs"]) == names

    def test_committed_plan_is_clean(self, reports):
        assert jaxnum.check_plan(str(PLAN_FILE), reports=reports) == []

    def test_every_committed_finding_has_a_reason(self):
        plan = jaxnum.load_plan(str(PLAN_FILE))
        triaged = 0
        for name, prog in plan["programs"].items():
            for key, f in prog.get("findings", {}).items():
                assert f.get("suppressed"), \
                    f"{name}: {key} committed without a triage reason"
                assert len(f["suppressed"]) > 20, \
                    f"{name}: {key} reason is not a reason"
                triaged += 1
        assert triaged >= 10   # the registry is not finding-free

    def test_diff_plans_drift_detection(self, reports):
        committed = jaxnum.load_plan(str(PLAN_FILE))
        current = jaxnum._plan_payload(reports)
        assert jaxnum.diff_plans(committed, current) == []

        drifted = copy.deepcopy(current)
        codec = drifted["programs"]["serving.kv_block_codec"]
        codec["max_error_ulps"] *= 2          # > 5% numeric drift
        v = jaxnum.diff_plans(committed, drifted)
        assert any("max_error_ulps drifted" in m for m in v)

        missing = copy.deepcopy(current)
        del missing["programs"]["train_step"]
        v = jaxnum.diff_plans(committed, missing)
        assert any("no longer in the registry" in m for m in v)
        v = jaxnum.diff_plans(missing, current)
        assert any("missing from the committed plan" in m for m in v)

        unsup = copy.deepcopy(current)
        fs = unsup["programs"]["train_step"]["findings"]
        fs[next(iter(fs))]["suppressed"] = None
        v = jaxnum.diff_plans(committed, unsup)
        assert any("suppression changed" in m for m in v)

    def test_small_bound_wobble_tolerated(self, reports):
        committed = jaxnum.load_plan(str(PLAN_FILE))
        wobbled = copy.deepcopy(jaxnum._plan_payload(reports))
        entry = wobbled["programs"]["serving.kv_block_codec"]
        entry["max_error_ulps"] *= 1.02       # inside the 5% tolerance
        assert jaxnum.diff_plans(committed, wobbled) == []


# ----------------------------------------------------------------- CLI
def _run_cli(*args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, str(JAXNUM_CLI), *args],
        capture_output=True, text=True, timeout=timeout, env=env)


class TestCLI:
    def test_check_committed_plan_exits_0(self):
        res = _run_cli("--plan", "check")
        assert res.returncode == 0, res.stdout + res.stderr
        assert "0 plan violation" in res.stdout

    def test_seeded_drift_exits_1(self, tmp_path):
        plan = json.loads(PLAN_FILE.read_text())
        plan["programs"]["serving.kv_block_codec"]["max_error_ulps"] /= 2
        drifted = tmp_path / "numplan.json"
        drifted.write_text(json.dumps(plan))
        res = _run_cli("--plan", "check", "--plan-file", str(drifted))
        assert res.returncode == 1, res.stdout + res.stderr
        assert "PLAN VIOLATION" in res.stdout

    def test_missing_plan_exits_1(self, tmp_path):
        res = _run_cli("--plan", "check", "--plan-file",
                       str(tmp_path / "absent.json"))
        assert res.returncode == 1
        assert "no committed precision plan" in res.stdout

    def test_usage_errors_exit_2(self):
        res = _run_cli("--plan", "check", "--programs", "train_step")
        assert res.returncode == 2
        res = _run_cli("--programs", "no.such.program")
        assert res.returncode == 2
        assert "unknown program" in (res.stdout + res.stderr)


# ---------------------------------------------------- quant_ops pins
class TestQuantOpsRegressions:
    def test_zero_point_outside_round_tie_parity(self):
        """saturate(round(x/scale) + zp): x=0.5, scale=1, zp=1 must
        give round(0.5)+1 = 1 (round-half-to-even), NOT the folded
        round(1.5) = 2."""
        from paddle_tpu.ops.quant_ops import quantize_linear
        q = quantize_linear(jnp.asarray([0.5, 2.5, -0.5]),
                            jnp.asarray(1.0), zero_point=1.0)
        assert np.asarray(q._value).tolist() == [1, 3, 1]

    def test_range_abs_max_zero_restart_batch_is_finite(self):
        """Window-restart step with an all-zero batch: out_scale is
        exactly 0 and the divide must be guarded, not NaN."""
        from paddle_tpu.ops.quant_ops import fake_quantize_range_abs_max
        q, scale, it = fake_quantize_range_abs_max(
            jnp.zeros((4,), jnp.float32), jnp.asarray(3.0), iter=0,
            window_size=10)
        assert np.all(np.isfinite(np.asarray(q._value)))
        assert np.asarray(q._value).tolist() == [0.0] * 4
        assert float(scale._value) == 0.0     # the restart semantics


# ------------------------------------------------- jaxpr_audit opt-in
class TestAuditIntNarrowing:
    def test_opt_in_not_default(self):
        assert "int_narrowing" not in jaxpr_audit.DEFAULT_CHECKS
        assert "int_narrowing" in jaxpr_audit.ALL_CHECKS

    def test_narrowing_flagged_only_when_opted_in(self):
        x = jnp.zeros((4,), jnp.int64)

        def f(x):
            return x.astype(jnp.int32)

        default = jaxpr_audit.audit_fn(f, x)
        assert default == []
        opted = jaxpr_audit.audit_fn(f, x, checks=("int_narrowing",))
        assert [i.kind for i in opted] == ["int_narrowing"]
        assert "NUM-CAST" in opted[0].message
