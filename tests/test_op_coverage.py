"""Mechanical op-corpus coverage gate.

Extracts every forward-op name registered via REGISTER_OPERATOR /
REGISTER_OP_WITHOUT_GRADIENT in /root/reference/paddle/fluid/operators and
asserts each one is accounted for in exactly one way:

  * same-name entry in this framework's op registry,
  * RENAMES: registered here under a different (documented) name, or as a
    public API callable ("api:<dotted.path>"),
  * SUBSUMED: the capability exists as a redesigned TPU-native subsystem
    (evidence = repo file that implements it; the file's existence is
    asserted),
  * NA: not applicable on TPU/XLA, with a one-line reason.

A snapshot of the extracted list is kept in tests/data/reference_ops.txt so
the gate still runs where /root/reference is absent.
"""
import os
import re

import pytest

REF_OPS_DIR = "/root/reference/paddle/fluid/operators"
SNAPSHOT = os.path.join(os.path.dirname(__file__), "data",
                        "reference_ops.txt")

_PAT = re.compile(
    r"REGISTER_OPERATOR\(\s*\n?\s*([a-z0-9_]+)\s*,|"
    r"REGISTER_OP_WITHOUT_GRADIENT\(\s*\n?\s*([a-z0-9_]+)\s*,")


def extract_reference_ops():
    if not os.path.isdir(REF_OPS_DIR):
        with open(SNAPSHOT) as f:
            return sorted(line.strip() for line in f if line.strip())
    names = set()
    for root, _dirs, files in os.walk(REF_OPS_DIR):
        for fn in files:
            if not fn.endswith((".cc", ".cu", ".h")):
                continue
            text = open(os.path.join(root, fn), errors="ignore").read()
            for m in _PAT.finditer(text):
                n = m.group(1) or m.group(2)
                if n and not n.endswith(("_grad", "_grad2")):
                    names.add(n)
    names.discard("op_type")  # macro parameter name inside a #define, not an op
    return sorted(names)


from paddle_tpu.ops.op_renames import (  # noqa: E402
    RENAMES, SUBSUMED, NA, resolve_api,
)


_resolve_api = resolve_api


def test_snapshot_is_current():
    """When the reference tree is present, the snapshot must match it."""
    if not os.path.isdir(REF_OPS_DIR):
        pytest.skip("reference tree absent")
    live = extract_reference_ops()
    with open(SNAPSHOT) as f:
        snap = sorted(line.strip() for line in f if line.strip())
    assert live == snap, "tests/data/reference_ops.txt is stale"


def test_every_reference_op_is_accounted_for():
    import paddle_tpu  # noqa: F401  (fills the registry)
    from paddle_tpu.core.dispatch import _OP_REGISTRY

    ops = extract_reference_ops()
    unaccounted, bad_renames, bad_evidence = [], [], []
    for name in ops:
        if name in _OP_REGISTRY:
            continue
        if name in RENAMES:
            target = RENAMES[name]
            if target.startswith("api:"):
                if _resolve_api(target[4:]) is None:
                    bad_renames.append((name, target))
            elif target not in _OP_REGISTRY:
                bad_renames.append((name, target))
            continue
        if name in SUBSUMED:
            repo_root = os.path.dirname(os.path.dirname(__file__))
            if not os.path.exists(os.path.join(repo_root, SUBSUMED[name])):
                bad_evidence.append((name, SUBSUMED[name]))
            continue
        if name in NA:
            continue
        unaccounted.append(name)
    assert not unaccounted, f"ops with no account: {unaccounted}"
    assert not bad_renames, f"rename targets missing: {bad_renames}"
    assert not bad_evidence, f"subsumed evidence missing: {bad_evidence}"


def test_no_dead_map_entries():
    """Every map entry must correspond to a real reference op (guards
    against typos silently passing the gate)."""
    ops = set(extract_reference_ops())
    for d in (RENAMES, SUBSUMED, NA):
        dead = [k for k in d if k not in ops]
        assert not dead, f"map entries not in reference: {dead}"
