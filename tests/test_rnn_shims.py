"""StaticRNN / DynamicRNN / py_reader static-graph shims
(reference: fluid/layers/rnn.py StaticRNN usage, control_flow.py
DynamicRNN, reader.py:149 create_py_reader).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    static.global_scope().drop_kids()
    with paddle.utils.unique_name.guard():
        paddle.enable_static()
        yield
        paddle.disable_static()


def test_static_rnn_matches_simple_rnn_math():
    """A StaticRNN computing h_t = tanh(x_t W + h_{t-1} U) must equal the
    hand-rolled numpy recurrence (the same math nn.layer.rnn.SimpleRNN
    runs in dygraph)."""
    T, B, D, H = 5, 3, 4, 6
    rs = np.random.RandomState(0)
    xv = rs.randn(T, B, D).astype(np.float32)
    h0v = np.zeros((B, H), np.float32)
    Wv = rs.randn(D, H).astype(np.float32) * 0.3
    Uv = rs.randn(H, H).astype(np.float32) * 0.3

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [T, B, D], "float32")
        h0 = static.data("h0", [B, H], "float32")
        W = static.data("W", [D, H], "float32")
        U = static.data("U", [H, H], "float32")
        rnn = static.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            prev = rnn.memory(init=h0)
            h = paddle.tanh(paddle.matmul(xt, W)
                            + paddle.matmul(prev, U))
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()

    exe = static.Executor()
    exe.run(startup)
    got = exe.run(main, feed={"x": xv, "h0": h0v, "W": Wv, "U": Uv},
                  fetch_list=[out])[0]

    # numpy oracle
    h = h0v
    expect = []
    for t in range(T):
        h = np.tanh(xv[t] @ Wv + h @ Uv)
        expect.append(h)
    np.testing.assert_allclose(got, np.stack(expect), rtol=1e-5,
                               atol=1e-6)


def test_static_rnn_zero_init_memory():
    """memory(shape=..., value=...) without init: zero-filled carry
    created in the startup program."""
    T, B, D = 4, 2, 3
    rs = np.random.RandomState(1)
    xv = rs.randn(T, B, D).astype(np.float32)

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [T, B, D], "float32")
        rnn = static.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(shape=[B, D], value=0.0)
            s = acc + xt
            rnn.update_memory(acc, s)
            rnn.step_output(s)
        out = rnn()
    exe = static.Executor()
    exe.run(startup)
    got = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
    np.testing.assert_allclose(got, np.cumsum(xv, axis=0), rtol=1e-6)


def test_dynamic_rnn_respects_lengths():
    """DynamicRNN over padded [B, T, D] + lengths: rows stop at their
    length (memory held, outputs zeroed past the end) — the reference's
    LoD-bucketed execution row for row."""
    B, T, D = 3, 5, 2
    rs = np.random.RandomState(2)
    xv = rs.randn(B, T, D).astype(np.float32)
    lens = np.array([5, 3, 1], np.int64)

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [B, T, D], "float32")
        lv = static.data("lens", [B], "int64")
        drnn = static.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, lengths=lv)
            acc = drnn.memory(shape=[B, D], value=0.0)
            s = acc + xt
            drnn.update_memory(acc, s)
            drnn.output(s)
        out = drnn()
    exe = static.Executor()
    exe.run(startup)
    got = exe.run(main, feed={"x": xv, "lens": lens},
                  fetch_list=[out])[0]  # [B, T, D]

    for b in range(B):
        run = np.cumsum(xv[b, :lens[b]], axis=0)
        np.testing.assert_allclose(got[b, :lens[b]], run, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(got[b, lens[b]:], 0.0)


def test_py_reader_feeds_executor_and_signals_eof():
    """py_reader: exe.run() without a feed dict drains the async queue;
    exhaustion raises EOFError (reference EOFException contract); reset +
    start replays the next epoch."""
    main = static.Program()
    startup = static.Program()
    rs = np.random.RandomState(3)
    batches = [(rs.randn(4, 3).astype(np.float32),) for _ in range(5)]
    with static.program_guard(main, startup):
        reader = static.py_reader(capacity=4, shapes=[[4, 3]],
                                  dtypes=["float32"])
        x = static.read_file(reader)
        out = (x * 2.0).sum()
    reader.decorate_batch_generator(lambda: iter(batches))

    exe = static.Executor()
    exe.run(startup)
    for epoch in range(2):
        reader.start()
        got = []
        while True:
            try:
                got.append(float(exe.run(main, fetch_list=[out])[0]))
            except EOFError:
                break
        assert len(got) == 5
        expect = [float(b[0].sum() * 2.0) for b in batches]
        np.testing.assert_allclose(got, expect, rtol=1e-5)
        reader.reset()
