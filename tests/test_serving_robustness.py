"""Hardened serving runtime — deadlines, admission control, watchdog +
crash recovery, and fault-injected chaos (paddle_tpu/inference/serving/
+ paddle_tpu/testing/faults.ServingFaultInjector).

The load-bearing pins (docs/serving.md "Failure semantics"):
- every abnormal exit is a terminal RequestOutput with a taxonomy
  finish_reason ('timeout' | 'shed' | 'error'), never a lost request;
- a poisoned/wedged step costs the offending request only: survivors
  are rebuilt by re-prefill and their tokens stay BITWISE-identical to
  an unfaulted run;
- the block pool never leaks across any mix of completion, expiry,
  cancellation, shedding and crash recovery (check_integrity after
  every scenario, including a 200-event random churn).
"""
import os
import time

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
import paddle_tpu.models.generation as gen
from paddle_tpu.inference.serving import (EngineConfig, EngineOverloaded,
                                          LLMEngine, SamplingParams)
from paddle_tpu.inference.serving.scheduler import (Request, RequestState,
                                                    Scheduler,
                                                    SchedulerConfig)
from paddle_tpu.inference.serving.paged_cache import PagedKVCache
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    return m


def _engine(model, faults=None, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("max_num_seqs", 4)
    return LLMEngine.from_model(model, EngineConfig(**kw), faults=faults)


def _prompts(n, seed=7, lo=3, hi=8):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, int(rng.randint(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _reference_tokens(model, prompt, max_new):
    out = np.asarray(gen.generate(
        model, jnp.asarray(np.asarray(prompt)[None], jnp.int32), max_new))
    return out[0, len(prompt):]


# --------------------------------------------------------- deadlines / TTL
def test_queue_ttl_expires_waiting_request(model):
    eng = _engine(model, max_num_seqs=1)
    p = _prompts(2)
    eng.add_request(p[0], SamplingParams(max_tokens=3))
    doomed = eng.add_request(p[1], SamplingParams(max_tokens=3,
                                                 queue_ttl_s=0.0))
    time.sleep(0.01)
    outs = eng.step()
    t = [o for o in outs if o.request_id == doomed]
    assert len(t) == 1 and t[0].finished
    assert t[0].finish_reason == "timeout" and t[0].new_token is None
    assert eng.get_request(doomed).state == RequestState.FINISHED_TIMEOUT
    assert eng.stats.expired == 1
    eng.run()
    eng.cache.check_integrity()


def test_deadline_aborts_running_request(model):
    eng = _engine(model, max_num_seqs=1)
    rid = eng.add_request(_prompts(1)[0],
                          SamplingParams(max_tokens=16, deadline_s=0.05))
    eng.step()                               # admit + prefill + first token
    assert eng.get_request(rid).state == RequestState.RUNNING
    time.sleep(0.08)
    outs = eng.step()                        # step boundary: overdue abort
    t = [o for o in outs if o.request_id == rid]
    assert t and t[-1].finish_reason == "timeout"
    assert eng.get_request(rid).state == RequestState.FINISHED_TIMEOUT
    assert eng.stats.timeouts == 1
    # partial progress is reported in the terminal output
    assert t[-1].token_ids == list(eng.get_request(rid).output_ids)
    assert not eng.has_unfinished()
    eng.cache.check_integrity()


# --------------------------------------------------------- admission control
def test_bounded_queue_rejects_when_full(model):
    eng = _engine(model, max_num_seqs=1, max_waiting=2)
    p = _prompts(3)
    eng.add_request(p[0], SamplingParams(max_tokens=2))
    eng.add_request(p[1], SamplingParams(max_tokens=2))
    with pytest.raises(EngineOverloaded) as ei:
        eng.add_request(p[2], SamplingParams(max_tokens=2))
    assert ei.value.depth == 2 and ei.value.limit == 2
    eng.run()
    eng.cache.check_integrity()


def test_shed_oldest_evicts_and_streams_terminal(model):
    eng = _engine(model, max_num_seqs=1, max_waiting=1,
                  admission_policy="shed_oldest")
    p = _prompts(2)
    victim = eng.add_request(p[0], SamplingParams(max_tokens=2))
    keeper = eng.add_request(p[1], SamplingParams(max_tokens=2))
    assert eng.get_request(victim).state == RequestState.FINISHED_SHED
    outs = eng.step()
    t = [o for o in outs if o.request_id == victim]
    assert t and t[0].finish_reason == "shed" and t[0].new_token is None
    assert eng.stats.shed == 1
    eng.run()
    assert eng.get_request(keeper).state == RequestState.FINISHED_LENGTH
    eng.cache.check_integrity()


def test_cache_high_watermark_pauses_admission(model):
    # 8 blocks, watermark 0.45 → hold above 3.6 blocks: the head's
    # 7-token prompt (2 blocks) admits freely (nothing running yet), the
    # second's 2 more would cross the mark with a live decode → held
    eng = _engine(model, num_blocks=8, max_num_seqs=4,
                  cache_high_watermark=0.45)
    p = _prompts(2, lo=7, hi=8)              # 2 blocks each at admission
    a = eng.add_request(p[0], SamplingParams(max_tokens=8))
    b = eng.add_request(p[1], SamplingParams(max_tokens=8))
    eng.step()
    # head admitted (nothing was running), second held by the watermark
    assert eng.get_request(a).state == RequestState.RUNNING
    assert eng.get_request(b).state == RequestState.WAITING
    assert eng.scheduler.watermark_holds >= 1
    eng.run()
    assert eng.get_request(b).finished
    eng.cache.check_integrity()


# ------------------------------------------------- anomaly guard + recovery
def test_prefill_nan_quarantines_only_offender(model):
    # nan_logits fires on the FIRST logits at/after step 1 = the first
    # prefill; its request errors out, the rest run to completion clean
    fi = ServingFaultInjector("nan_logits@1")
    eng = _engine(model, faults=fi)
    p = _prompts(3)
    rids = [eng.add_request(q, SamplingParams(max_tokens=5)) for q in p]
    res = eng.run()
    assert eng.get_request(rids[0]).state == RequestState.FINISHED_ERROR
    assert eng.stats.errors == 1 and eng.stats.recoveries == 0
    for q, rid in zip(p[1:], rids[1:]):
        np.testing.assert_array_equal(res[rid], _reference_tokens(model, q, 5))
    eng.cache.check_integrity()


def test_decode_nan_recovery_keeps_survivors_bitwise(model):
    # all four prefill at step 1; step 2 is pure decode (one fused
    # chunk drains the remaining tokens), so the poison lands on decode
    # row 1 of that chunk → the WHOLE chunk is discarded, that request
    # quarantined, the other three rebuilt by re-prefill and
    # BITWISE-equal to the unfaulted reference (chunk-invariant
    # sampling keys make the replay exact)
    fi = ServingFaultInjector("nan_logits@2:1")
    eng = _engine(model, faults=fi)
    p = _prompts(4)
    rids = [eng.add_request(q, SamplingParams(max_tokens=6)) for q in p]
    res = eng.run()
    errored = [r for r in rids
               if eng.get_request(r).state == RequestState.FINISHED_ERROR]
    assert len(errored) == 1
    assert eng.stats.errors == 1 and eng.stats.recoveries == 1
    assert eng.stats.rebuilt == 3
    assert ("nan_logits", 2) in fi.fired_log
    for q, rid in zip(p, rids):
        if rid in errored:
            continue
        np.testing.assert_array_equal(res[rid],
                                      _reference_tokens(model, q, 6))
    eng.cache.check_integrity()


def test_cache_corruption_detected_and_recovered(model):
    # NaN scribbled into a live block surfaces as non-finite decode
    # logits on that sequence; recovery scrubs + rebuilds, and the pool
    # must come back clean (a NaN left in a freed block would poison
    # whoever gets it next via 0*NaN through the attention mask)
    fi = ServingFaultInjector("cache_corrupt@2")
    eng = _engine(model, faults=fi)
    p = _prompts(4)
    rids = [eng.add_request(q, SamplingParams(max_tokens=6)) for q in p]
    res = eng.run()
    assert eng.stats.errors >= 1 and eng.stats.recoveries >= 1
    errored = {r for r in rids
               if eng.get_request(r).state == RequestState.FINISHED_ERROR}
    for q, rid in zip(p, rids):
        if rid not in errored:
            np.testing.assert_array_equal(
                res[rid], _reference_tokens(model, q, 6))
    eng.cache.check_integrity()
    for kp, vp in eng.cache.pools:           # scrub left no NaN behind
        assert bool(jnp.isfinite(kp).all()) and bool(jnp.isfinite(vp).all())


def test_stall_trips_watchdog_and_engine_drains(model):
    # generous timeout (2s) so tiny-model compiles can't trip it; the
    # injected stall (2.5s) must. Warm the jit caches with a clean run
    # first so compile time never lands inside the guarded step.
    clean = _engine(model)
    for q in _prompts(4):
        clean.add_request(q, SamplingParams(max_tokens=4))
    clean.run()
    fi = ServingFaultInjector("stall@2:2.5")
    eng = _engine(model, faults=fi, step_timeout_s=2.0)
    rids = [eng.add_request(q, SamplingParams(max_tokens=4))
            for q in _prompts(4)]
    eng.run()
    assert eng.stats.watchdog_trips >= 1
    assert eng.stats.errors >= 1            # the quarantined head
    assert all(eng.get_request(r).finished for r in rids)
    eng.cache.check_integrity()


# -------------------------------------------------------- heartbeat wiring
def test_engine_step_beats_elastic_heartbeat(model, tmp_path):
    hb = tmp_path / "beat"
    os.environ["PADDLE_ELASTIC_HEARTBEAT_FILE"] = str(hb)
    try:
        eng = _engine(model)
        eng.add_request(_prompts(1)[0], SamplingParams(max_tokens=2))
        eng.step()
        assert hb.exists()
        before = hb.stat().st_mtime_ns
        time.sleep(0.01)
        eng.step()
        assert hb.stat().st_mtime_ns > before
    finally:
        del os.environ["PADDLE_ELASTIC_HEARTBEAT_FILE"]


# ------------------------------------------------------ starvation / FCFS
def test_requeue_preserves_arrival_order():
    """A preempted-and-requeued request re-enters the waiting queue at
    its ORIGINAL FCFS position, ahead of later arrivals (appendleft
    would also pass this one, but inverts multi-request recovery order —
    covered below)."""
    cache = PagedKVCache(num_layers=1, num_heads=2, head_dim=4,
                         num_blocks=16, block_size=4)
    sched = Scheduler(SchedulerConfig(max_num_seqs=4), cache)
    reqs = [Request(request_id=f"r{i}", prompt_ids=np.ones(3, np.int32),
                    params=SamplingParams(max_tokens=4)) for i in range(4)]
    for r in reqs:
        sched.add(r)
    sched.schedule()                         # all running
    assert [r.request_id for r in sched.running] == ["r0", "r1", "r2", "r3"]
    late = Request(request_id="late", prompt_ids=np.ones(3, np.int32),
                   params=SamplingParams(max_tokens=4))
    sched.add(late)
    # recovery requeue of r1 then r3 (any order) must land them BEFORE
    # the later arrival and in arrival order relative to each other
    sched.requeue_for_recovery(reqs[3])
    sched.requeue_for_recovery(reqs[1])
    assert [r.request_id for r in sched.waiting] == ["r1", "r3", "late"]
    cache.check_integrity()


def test_repeatedly_preempted_request_not_starved(model):
    """Engine-level regression: under constant pool pressure with a
    stream of later arrivals, the earliest request still finishes no
    later than any later arrival (strict FCFS despite preemptions)."""
    eng = _engine(model, num_blocks=6, max_num_seqs=2)
    first = eng.add_request(_prompts(1, seed=3, lo=6, hi=7)[0],
                            SamplingParams(max_tokens=10))
    later = []
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 200
        if steps % 2 == 0 and len(later) < 6:
            later.append(eng.add_request(
                _prompts(1, seed=40 + steps, lo=4, hi=6)[0],
                SamplingParams(max_tokens=6)))
    t_first = eng.get_request(first).finish_time
    for rid in later:
        assert t_first <= eng.get_request(rid).finish_time
    eng.cache.check_integrity()


# ----------------------------------------------------- cancellation races
def test_cancel_waiting_request_before_prefill(model):
    eng = _engine(model, max_num_seqs=1)
    p = _prompts(2)
    eng.add_request(p[0], SamplingParams(max_tokens=3))
    queued = eng.add_request(p[1], SamplingParams(max_tokens=3))
    assert eng.cancel(queued)                # still WAITING: never ran
    outs = eng.step()
    t = [o for o in outs if o.request_id == queued]
    assert t and t[0].finish_reason == "cancelled"
    eng.run()
    assert eng.get_request(queued).output_ids == []
    eng.cache.check_integrity()


def test_cancel_expired_request_is_noop(model):
    eng = _engine(model, max_num_seqs=1)
    p = _prompts(2)
    eng.add_request(p[0], SamplingParams(max_tokens=3))
    doomed = eng.add_request(p[1], SamplingParams(max_tokens=3,
                                                 queue_ttl_s=0.0))
    time.sleep(0.01)
    eng.step()                               # expires `doomed`
    assert eng.get_request(doomed).state == RequestState.FINISHED_TIMEOUT
    assert not eng.cancel(doomed)            # lost the race: no double-free
    assert eng.stats.cancelled == 0
    eng.run()
    eng.cache.check_integrity()


def test_churn_cancel_expire_complete_leaks_nothing(model):
    """200 random request fates (complete / cancel / expire / shed) with
    recovery faults mixed in: the pool must end with every block free and
    lifetime counters balanced."""
    fi = ServingFaultInjector("nan_logits@9,cache_corrupt@21,nan_logits@33")
    eng = _engine(model, num_blocks=32, max_num_seqs=4, max_waiting=8,
                  admission_policy="shed_oldest")
    rng = np.random.RandomState(0)
    submitted = []
    n_target = 200
    steps = 0
    while len(submitted) < n_target or eng.has_unfinished():
        if len(submitted) < n_target and rng.rand() < 0.7:
            ttl = 0.0 if rng.rand() < 0.1 else None
            rid = eng.add_request(
                rng.randint(0, VOCAB, int(rng.randint(3, 7))).astype(
                    np.int32),
                SamplingParams(max_tokens=int(rng.randint(2, 5)),
                               queue_ttl_s=ttl))
            submitted.append(rid)
        if submitted and rng.rand() < 0.15:
            eng.cancel(submitted[int(rng.randint(len(submitted)))])
        eng.step()
        steps += 1
        assert steps < 3000
    assert len(submitted) == n_target
    for rid in submitted:
        assert eng.get_request(rid).finished, f"lost request {rid}"
    assert eng.cache.num_free() == eng.cache.num_blocks
    assert eng.cache.blocks_allocated == eng.cache.blocks_freed
    eng.cache.check_integrity()


# ----------------------------------------------------- chaos acceptance
@pytest.mark.chaos
def test_chaos_sixteen_requests_through_faults(model):
    """The PR's acceptance pin: 16 staggered requests through a seeded
    nan/stall/cache-corrupt schedule — every request terminal, zero
    leaked blocks, at least one quarantine, and every surviving request
    bitwise-identical to generate()."""
    fi = ServingFaultInjector(
        "nan_logits@4,stall@7:0.1,cache_corrupt@10,nan_logits@13")
    eng = _engine(model, faults=fi, num_blocks=64, max_num_seqs=4,
                  max_waiting=16, admission_policy="shed_oldest",
                  cache_high_watermark=0.9)
    rng = np.random.RandomState(0)
    specs = [(rng.randint(0, VOCAB, int(rng.randint(3, 9))).astype(np.int32),
              int(rng.randint(4, 10))) for _ in range(16)]
    pending = list(specs)
    rids = []
    for p, mt in pending[:4]:
        rids.append(eng.add_request(p, SamplingParams(max_tokens=mt)))
    pending = pending[4:]
    steps = 0
    while eng.has_unfinished() or pending:
        eng.step()
        steps += 1
        assert steps < 400
        if steps % 2 == 0 and pending:
            p, mt = pending.pop(0)
            rids.append(eng.add_request(p, SamplingParams(max_tokens=mt)))
    assert len(rids) == 16
    for rid in rids:
        assert eng.get_request(rid).finished, f"lost request {rid}"
    assert eng.stats.errors >= 1             # the schedule really bit
    assert len(fi.fired_log) == 4            # every fault fired
    eng.cache.check_integrity()
    survivors = 0
    for (p, mt), rid in zip(specs, rids):
        req = eng.get_request(rid)
        if req.state in (RequestState.FINISHED_STOPPED,
                         RequestState.FINISHED_LENGTH):
            survivors += 1
            np.testing.assert_array_equal(
                np.asarray(req.output_ids, np.int64),
                _reference_tokens(model, p, mt))
    assert survivors >= 8                    # faults cost few, not most
