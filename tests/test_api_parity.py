"""Top-level namespace parity with the reference python/paddle/__init__.py
(mechanical audit, same spirit as tests/test_op_coverage.py for ops) +
behaviour tests for the distribution module and fluid-style aliases.
"""
import math
import re

import numpy as np
import pytest

import paddle_tpu as paddle

REF_INIT = "/root/reference/python/paddle/__init__.py"


def _names_from_source(path, use_all=False):
    """AST-walk a reference module: every `from X import a as b` exports
    b (the __init__ convention), plus `import paddle.x` submodules; for
    plain module files an explicit __all__ wins when use_all."""
    import ast as _ast
    tree = _ast.parse(open(path).read())
    if use_all:
        for node in tree.body:
            if isinstance(node, _ast.Assign) and any(
                    isinstance(t, _ast.Name) and t.id == "__all__"
                    for t in node.targets):
                try:
                    vals = _ast.literal_eval(node.value)
                    return {n for n in vals if not n.startswith("_")}
                except ValueError:
                    break
    names = set()
    for node in _ast.walk(tree):
        if isinstance(node, _ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                n = a.asname or a.name
                if n != "*" and not n.startswith("_"):
                    names.add(n)
        elif isinstance(node, _ast.Import):
            for a in node.names:
                if a.name.startswith("paddle."):
                    names.add(a.name.split(".")[1])
    return names


def _reference_top_level_names():
    return _names_from_source(REF_INIT)


def test_top_level_namespace_parity():
    missing = sorted(n for n in _reference_top_level_names()
                     if not hasattr(paddle, n))
    assert not missing, f"paddle.* names missing vs reference: {missing}"


# -- distribution ------------------------------------------------------------

def test_uniform_distribution():
    paddle.seed(0)
    u = paddle.distribution.Uniform(1.0, 3.0)
    s = u.sample([2000])
    arr = s.numpy()
    assert arr.shape == (2000,)
    assert arr.min() >= 1.0 and arr.max() <= 3.0
    assert abs(arr.mean() - 2.0) < 0.1
    np.testing.assert_allclose(float(u.entropy().numpy()),
                               math.log(2.0), rtol=1e-6)
    lp = u.log_prob(paddle.to_tensor([2.0, 5.0]))
    np.testing.assert_allclose(lp.numpy()[0], math.log(0.5), rtol=1e-6)
    assert lp.numpy()[1] == -np.inf  # outside support
    np.testing.assert_allclose(
        u.probs(paddle.to_tensor([2.0])).numpy()[0], 0.5, rtol=1e-6)


def test_normal_distribution_and_kl():
    paddle.seed(0)
    n = paddle.distribution.Normal(0.0, 2.0)
    s = n.sample([4000])
    arr = s.numpy()
    assert abs(arr.mean()) < 0.15 and abs(arr.std() - 2.0) < 0.15
    # entropy: 0.5 log(2 pi e sigma^2)
    want = 0.5 * math.log(2 * math.pi * math.e * 4.0)
    np.testing.assert_allclose(float(n.entropy().numpy()), want, rtol=1e-5)
    v = paddle.to_tensor([1.0])
    want_lp = -0.5 * (1.0 / 4.0) - math.log(2.0) \
        - 0.5 * math.log(2 * math.pi)
    np.testing.assert_allclose(n.log_prob(v).numpy()[0], want_lp,
                               rtol=1e-5)
    np.testing.assert_allclose(n.probs(v).numpy()[0],
                               math.exp(want_lp), rtol=1e-5)
    other = paddle.distribution.Normal(1.0, 1.0)
    # KL(N(0,2)||N(1,1)) = log(s1/s0) + (s0^2+(m0-m1)^2)/(2 s1^2) - 1/2
    want_kl = math.log(1.0 / 2.0) + (4.0 + 1.0) / 2.0 - 0.5
    np.testing.assert_allclose(float(n.kl_divergence(other).numpy()),
                               want_kl, rtol=1e-5)


def test_categorical_distribution():
    paddle.seed(0)
    logits = paddle.to_tensor([0.0, math.log(3.0)])  # probs 0.25/0.75
    c = paddle.distribution.Categorical(logits)
    s = c.sample([3000]).numpy()
    assert set(np.unique(s)) <= {0, 1}
    assert abs(s.mean() - 0.75) < 0.05
    want_h = -(0.25 * math.log(0.25) + 0.75 * math.log(0.75))
    np.testing.assert_allclose(float(c.entropy().numpy()), want_h,
                               rtol=1e-5)
    np.testing.assert_allclose(
        c.probs(paddle.to_tensor([0, 1])).numpy(), [0.25, 0.75],
        rtol=1e-5)
    np.testing.assert_allclose(
        c.log_prob(paddle.to_tensor([1])).numpy(), [math.log(0.75)],
        rtol=1e-5)
    d = paddle.distribution.Categorical(paddle.to_tensor([0.0, 0.0]))
    kl = float(c.kl_divergence(d).numpy())
    want_kl = (0.25 * math.log(0.25 / 0.5) + 0.75 * math.log(0.75 / 0.5))
    np.testing.assert_allclose(kl, want_kl, rtol=1e-5)


def test_categorical_batched_sample_shape():
    paddle.seed(0)
    logits = paddle.to_tensor(np.zeros((4, 6), np.float32))
    c = paddle.distribution.Categorical(logits)
    s = c.sample([2, 3])
    assert list(s.shape) == [2, 3, 4]


# -- fluid-style aliases -----------------------------------------------------

def test_elementwise_axis_broadcast():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    y = paddle.to_tensor(np.array([10.0, 20.0, 30.0], np.float32))
    out = paddle.elementwise_add(x, y, axis=1)  # y aligned to dim 1
    want = x.numpy() + y.numpy().reshape(1, 3, 1)
    np.testing.assert_allclose(out.numpy(), want)
    out2 = paddle.elementwise_sub(x, paddle.to_tensor(
        np.ones(4, np.float32)))
    np.testing.assert_allclose(out2.numpy(), x.numpy() - 1.0)


def test_reduce_aliases_and_overflow_checks():
    x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(
        paddle.reduce_sum(x, dim=1, keep_dim=True).numpy(), [[3.0], [7.0]])
    np.testing.assert_allclose(float(paddle.reduce_prod(x).numpy()), 24.0)
    assert not bool(paddle.has_inf(x).numpy())
    assert bool(paddle.has_nan(
        paddle.to_tensor([np.nan, 1.0])).numpy())


def test_tanh_inplace():
    x = paddle.to_tensor(np.array([0.0, 1.0], np.float32))
    out = paddle.tanh_(x)
    np.testing.assert_allclose(x.numpy(), np.tanh([0.0, 1.0]), rtol=1e-6)
    assert out is x or np.allclose(out.numpy(), x.numpy())


def test_batch_reader():
    def reader():
        for i in range(5):
            yield i
    batches = list(paddle.batch(reader, 2)())
    assert batches == [[0, 1], [2, 3], [4]]
    batches = list(paddle.batch(reader, 2, drop_last=True)())
    assert batches == [[0, 1], [2, 3]]
    with pytest.raises(ValueError):
        paddle.batch(reader, 0)


def test_compat_and_misc():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.compat.round(2.5) == 3.0
    assert paddle.compat.round(-2.5) == -3.0
    assert paddle.get_cudnn_version() is None
    assert paddle.is_compiled_with_xpu() is False
    assert paddle.framework.VarBase is paddle.Tensor
    assert paddle.VarBase is paddle.Tensor
    import os
    assert os.path.isdir(os.path.dirname(paddle.sysconfig.get_include()))
    with pytest.raises(NotImplementedError):
        paddle.onnx.export(None, "/tmp/x")
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    paddle.set_printoptions(precision=4)
    np.set_printoptions()  # restore defaults for other tests


def _reference_module_names(relpath):
    """Exported names of a reference submodule: its __all__ when declared
    (plain module files), else its imports (the __init__ convention)."""
    import os
    base = "/root/reference/python/paddle"
    p = os.path.join(base, *relpath.split("."))
    plain = os.path.isfile(p + ".py")
    p = p + ".py" if plain else os.path.join(p, "__init__.py")
    return _names_from_source(p, use_all=plain)


def test_submodule_namespace_parity():
    """Same mechanical audit as the top-level test, across the public
    submodules a reference user imports from."""
    import paddle_tpu as p
    mods = {
        "nn": p.nn, "nn.functional": p.nn.functional,
        "tensor": p.ops, "optimizer": p.optimizer,
        "optimizer.lr": p.optimizer.lr, "static": p.static,
        "io": p.io, "metric": p.metric, "amp": p.amp, "jit": p.jit,
        "distributed": p.distributed, "text": p.text,
        "vision": p.vision, "vision.transforms": p.vision.transforms,
        "vision.models": p.vision.models,
        "vision.datasets": p.vision.datasets, "vision.ops": p.vision.ops,
    }
    problems = {}
    for name, mod in mods.items():
        missing = sorted(n for n in _reference_module_names(name)
                         if not hasattr(mod, n))
        if missing:
            problems[name] = missing
    assert not problems, f"submodule names missing vs reference: {problems}"


# -- decode API + new functionals -------------------------------------------

def test_beam_search_decoder_dynamic_decode():
    paddle.seed(0)
    cell = paddle.nn.GRUCell(8, 16)
    proj = paddle.nn.Linear(16, 12)
    emb = paddle.nn.Embedding(12, 8)
    dec = paddle.nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                      beam_size=4, embedding_fn=emb,
                                      output_fn=proj)
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(3, 16)
                          .astype(np.float32))
    outs, states = paddle.nn.dynamic_decode(dec, inits=h0, max_step_num=7)
    ids = outs["predicted_ids"].numpy()
    assert ids.shape == (3, 7, 4) and ids.min() >= 0 and ids.max() < 12
    # scores decrease along the beam axis (sorted topk)
    sc = outs["scores"].numpy()
    assert (np.diff(sc[:, -1, :], axis=-1) <= 1e-5).all()
    # beams of one batch row must come from that row's state only:
    # identical rows => identical beams
    h_same = paddle.to_tensor(np.zeros((2, 16), np.float32))
    o2, _ = paddle.nn.dynamic_decode(dec, inits=h_same, max_step_num=5)
    a, b = o2["predicted_ids"].numpy()
    np.testing.assert_array_equal(a, b)


def test_hsigmoid_loss_layer_trains():
    paddle.seed(0)
    layer = paddle.nn.HSigmoidLoss(8, 6)
    import paddle_tpu.optimizer as opt
    optim = opt.SGD(0.5, parameters=layer.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    lab = paddle.to_tensor(rng.randint(0, 6, (16, 1)))
    first = None
    for _ in range(15):
        loss = layer(x, lab).mean()
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        optim.step()
        optim.clear_grad()
    assert float(loss.numpy()) < first


def test_static_compat_helpers(tmp_path):
    import paddle_tpu.static as static
    # scope_guard actually swaps the global scope
    s = static.Scope()
    with static.scope_guard(s):
        assert static.global_scope() is s
    assert static.global_scope() is not s
    with static.name_scope("blockA") as ns:
        assert ns == "blockA"
    with static.device_guard("gpu:0"):
        pass
    assert len(static.cpu_places(2)) == 2
    # save_to_file/load_from_file round trip
    p = str(tmp_path / "blob.bin")
    static.save_to_file(p, b"xyz")
    assert static.load_from_file(p) == b"xyz"


def test_aux_namespace_parity():
    """utils / incubate / inference / reader / dataset — the remaining
    reference namespaces, audited the same mechanical way."""
    import paddle_tpu as p
    mods = {"utils": p.utils, "incubate": p.incubate,
            "inference": p.inference, "reader": p.reader,
            "dataset": p.dataset}
    problems = {}
    for name, mod in mods.items():
        # `import paddle.reader.decorator` inside reader/__init__ makes
        # the ast walker emit the module's own name — not an export
        missing = sorted(n for n in _reference_module_names(name)
                         if n != name and not hasattr(mod, n))
        if missing:
            problems[name] = missing
    assert not problems, f"aux namespaces missing: {problems}"


def test_reader_decorators():
    import paddle_tpu as p
    r10 = lambda: iter(range(10))
    assert sorted(p.reader.shuffle(r10, 4)()) == list(range(10))
    assert list(p.reader.firstn(r10, 3)()) == [0, 1, 2]
    assert list(p.reader.chain(r10, r10)()) == list(range(10)) * 2
    assert list(p.reader.map_readers(lambda a, b: a + b, r10, r10)()) == \
        [2 * i for i in range(10)]
    assert list(p.reader.compose(r10, r10)()) == \
        [(i, i) for i in range(10)]
    with pytest.raises(p.reader.ComposeNotAligned):
        list(p.reader.compose(r10, lambda: iter(range(5)))())
    assert sorted(p.reader.buffered(r10, 2)()) == list(range(10))
    out = list(p.reader.xmap_readers(lambda x: x * 2, r10, 3, 4,
                                     order=True)())
    assert out == [2 * i for i in range(10)]
    cached = p.reader.cache(r10)
    assert list(cached()) == list(cached())


def test_dataset_reader_adapters():
    import paddle_tpu as p
    img, lab = next(p.dataset.mnist.train()())
    assert img.shape == (784,) and 0 <= lab < 10
    x, y = next(p.dataset.uci_housing.test()())
    assert x.shape == (13,)
    ids, label = next(p.dataset.imdb.train(None)())
    assert isinstance(ids, list) and label in (0, 1)
    gram = next(p.dataset.imikolov.train(None, 5)())
    assert len(gram) >= 2
    # fluid-era pipeline end to end: batch over a dataset reader
    b = p.batch(p.dataset.uci_housing.train(), 8)
    first = next(b())
    assert len(first) == 8
    # image transforms
    im = np.arange(32 * 48 * 3, dtype=np.uint8).reshape(32, 48, 3)
    small = p.dataset.image.resize_short(im, 16)
    assert min(small.shape[:2]) == 16
    crop = p.dataset.image.center_crop(small, 12)
    assert crop.shape[:2] == (12, 12)
    chw = p.dataset.image.to_chw(crop)
    assert chw.shape[0] == 3
