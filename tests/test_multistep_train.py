"""MultiStepTrainStep: K optimizer steps per dispatch via lax.scan.

Parity contract: K scanned steps == K sequential TrainStep calls —
same losses, parameters, BN buffers and RNG (dropout) stream. The
reference analogue is train_from_dataset handing the loop to the C++
trainer (framework/multi_trainer.cc:1): Python leaves the per-step path.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt


def _make_model(seed):
    paddle.seed(seed)
    return paddle.nn.Sequential(
        paddle.nn.Linear(8, 16),
        paddle.nn.BatchNorm1D(16),
        paddle.nn.ReLU(),
        paddle.nn.Dropout(0.5),   # exercises the threaded RNG stream
        paddle.nn.Linear(16, 4),
    )


def _loss_fn(m, x, y):
    return F.cross_entropy(m(x), y)


def _batches(n, rng):
    xs = rng.standard_normal((n, 16, 8)).astype(np.float32)
    ys = rng.integers(0, 4, size=(n, 16)).astype(np.int64)
    return xs, ys


def test_multistep_parity_with_sequential():
    K, CALLS = 3, 2
    rng = np.random.default_rng(0)
    xs, ys = _batches(K * CALLS, rng)

    # sequential oracle: 6 TrainStep calls
    model_a = _make_model(7)
    opt_a = opt.Adam(1e-2, parameters=model_a.parameters())
    step_a = paddle.jit.TrainStep(model_a, _loss_fn, opt_a)
    losses_a = [float(step_a(paddle.to_tensor(xs[i]),
                             paddle.to_tensor(ys[i])).numpy())
                for i in range(K * CALLS)]

    # scanned path: 2 dispatches of 3 steps each
    model_b = _make_model(7)
    opt_b = opt.Adam(1e-2, parameters=model_b.parameters())
    step_b = paddle.jit.MultiStepTrainStep(model_b, _loss_fn, opt_b,
                                           steps=K)
    losses_b = []
    for c in range(CALLS):
        out = step_b(paddle.to_tensor(xs[c * K:(c + 1) * K]),
                     paddle.to_tensor(ys[c * K:(c + 1) * K]))
        assert out.shape == [K]
        losses_b.extend(np.asarray(out.numpy(), np.float64).tolist())

    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-5,
                               err_msg="loss trajectories diverge")
    sd_a, sd_b = model_a.state_dict(), model_b.state_dict()
    assert set(sd_a) == set(sd_b)
    for k in sd_a:  # params AND BN running stats
        np.testing.assert_allclose(sd_a[k].numpy(), sd_b[k].numpy(),
                                   rtol=1e-5, atol=1e-7, err_msg=k)
    assert opt_b._global_step == K * CALLS


def test_multistep_rejects_unstacked_batch():
    model = _make_model(0)
    optim = opt.SGD(1e-2, parameters=model.parameters())
    step = paddle.jit.MultiStepTrainStep(model, _loss_fn, optim, steps=4)
    x = paddle.randn([16, 8])          # missing the [steps, ...] stack
    y = paddle.to_tensor(np.zeros(16, np.int64))
    with pytest.raises(ValueError, match="stacked"):
        step(x, y)
    with pytest.raises(ValueError):
        paddle.jit.MultiStepTrainStep(model, _loss_fn, optim, steps=0)
