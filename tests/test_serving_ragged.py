"""Ragged paged attention + chunked prefill (ISSUE 10).

The load-bearing pins:

- the pallas kernel (interpret mode) is BITWISE-identical to its
  lax.scan reference and matches a dense softmax oracle to float32
  tolerance, dead rows included;
- engine output under kernel="ragged" is bitwise-identical to
  kernel="bucketed" and to the dense generate() reference — greedy AND
  stochastic. Off-TPU both kernels lower to the same gather path and
  row-wise results are batch-width-invariant, so CPU equality is
  structural; on TPU the kernel-level tolerance above is the bound and
  the greedy token streams still match exactly;
- ONE compilation of fused_decode_chunk covers every batch mix under
  ragged (the jit-cache pin that retires the per-bucket compile axis),
  while the bucketed fallback compiles per power-of-two bucket;
- chunked prefill (prefill_chunk_threshold) emits the same greedy
  tokens as the dense one-shot prefill path, invariant under chunk
  size, with EOS-mid-chunk, preemption/requeue and chaos recovery
  holding the zero-leak / zero-lost / survivor-bitwise contracts.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
import paddle_tpu.models.generation as gen
from paddle_tpu.inference.serving import (EngineConfig, LLMEngine,
                                          SamplingParams)
from paddle_tpu.inference.serving.attention import fused_decode_chunk
from paddle_tpu.ops.pallas import ragged_paged_attention as rpa
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    return m


def _engine(model, faults=None, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("max_num_seqs", 4)
    return LLMEngine.from_model(model, EngineConfig(**kw),
                                faults=faults)


def _reference_tokens(model, prompt, max_new):
    out = np.asarray(gen.generate(
        model, jnp.asarray(np.asarray(prompt)[None], jnp.int32), max_new))
    return out[0, len(prompt):]


def _run_engine(model, prompts, samplings, **kw):
    eng = _engine(model, **kw)
    rids = [eng.add_request(p, s) for p, s in zip(prompts, samplings)]
    res = eng.run(max_steps=500)
    return eng, rids, res


# ------------------------------------------------------- kernel parity
def _random_paged(seed, n, nb, bs, h, d):
    """Random pools + valid block tables + mixed lengths (one dead
    row, one single-token row, one near-capacity row)."""
    rng = np.random.RandomState(seed)
    mb = 5
    k_pool = rng.randn(nb, bs, h, d).astype(np.float32)
    v_pool = rng.randn(nb, bs, h, d).astype(np.float32)
    q = rng.randn(n, h, d).astype(np.float32)
    lengths = np.array([0, 1, bs * mb - 1, 7][:n], np.int32)
    perm = rng.permutation(nb)
    tables = np.full((n, mb), -1, np.int32)
    used = 0
    for i in range(n):
        need = -(-int(lengths[i]) // bs)
        tables[i, :need] = perm[used:used + need]
        used += need
    return (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray(lengths))


def _dense_oracle(q, k_pool, v_pool, tables, lengths):
    """Per-row gather + full softmax, float32."""
    n, h, d = q.shape
    bs = k_pool.shape[1]
    out = np.zeros((n, h, d), np.float32)
    for i in range(n):
        ln = int(lengths[i])
        if ln == 0:
            continue
        blocks = [int(b) for b in np.asarray(tables[i]) if b >= 0]
        kc = np.concatenate([np.asarray(k_pool[b]) for b in blocks])[:ln]
        vc = np.concatenate([np.asarray(v_pool[b]) for b in blocks])[:ln]
        s = np.einsum("hd,shd->hs", np.asarray(q[i]), kc) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hs,shd->hd", p, vc)
    return out


def test_kernel_interpret_bitwise_matches_reference():
    """The pallas kernel (interpret mode, runs on CPU) is bitwise-equal
    to the lax.scan reference — same flash update, same block order —
    and float32-close to a dense softmax oracle. The dead row (length
    0) returns exact zeros, the kernel-level form of 'dead rows cost
    zero work'."""
    args = _random_paged(0, 4, nb=16, bs=4, h=4, d=8)
    got = np.asarray(rpa.ragged_decode_attention(*args, interpret=True))
    ref = np.asarray(rpa.ragged_attention_reference(*args))
    np.testing.assert_array_equal(got, ref)
    oracle = _dense_oracle(*args)
    np.testing.assert_allclose(got, oracle, rtol=2e-6, atol=2e-6)
    assert np.all(got[0] == 0.0)          # lengths[0] == 0: dead row


# ------------------------------------------------------- engine parity
def test_greedy_ragged_bucketed_dense_bitwise(model):
    """THE tentpole pin: kernel='ragged' output == kernel='bucketed'
    output == dense generate(), token-exact, on a mixed-length
    workload."""
    prompts = [np.arange(1, 4, dtype=np.int32),
               np.arange(5, 12, dtype=np.int32),
               np.asarray([9, 1, 7, 3], np.int32)]
    samp = [SamplingParams(max_tokens=mt) for mt in (9, 5, 12)]
    _, rr, res_r = _run_engine(model, prompts, samp, kernel="ragged")
    _, rb, res_b = _run_engine(model, prompts, samp, kernel="bucketed")
    for r_r, r_b, p, s in zip(rr, rb, prompts, samp):
        np.testing.assert_array_equal(res_r[r_r], res_b[r_b])
        np.testing.assert_array_equal(
            res_r[r_r], _reference_tokens(model, p, s.max_tokens))


def test_stochastic_ragged_bucketed_parity(model):
    """Temperature/top-k/top-p streams match across kernels. Off-TPU
    this is bitwise (same lowered path, row-invariant padding); the
    TPU kernel's numeric envelope is bounded by the oracle test above,
    so any divergence here is a routing bug, not noise."""
    prompts = [np.arange(1, 4, dtype=np.int32),
               np.asarray([9, 1, 7, 3], np.int32),
               np.arange(5, 10, dtype=np.int32)]
    samp = [SamplingParams(max_tokens=10, temperature=0.9, top_k=9,
                           top_p=0.8, seed=11),
            SamplingParams(max_tokens=8, temperature=0.7, seed=22),
            SamplingParams(max_tokens=12, temperature=1.1, top_p=0.95,
                           seed=33)]
    _, rr, res_r = _run_engine(model, prompts, samp, kernel="ragged",
                               num_blocks=32)
    _, rb, res_b = _run_engine(model, prompts, samp, kernel="bucketed",
                               num_blocks=32)
    if jax.default_backend() == "tpu":
        pytest.skip("stochastic streams are knife-edge under the "
                    "kernel's 1e-6 envelope; the greedy test and the "
                    "kernel oracle carry the TPU contract")
    for r_r, r_b in zip(rr, rb):
        np.testing.assert_array_equal(res_r[r_r], res_b[r_b])
        assert np.all(res_r[r_r] >= 0) and np.all(res_r[r_r] < VOCAB)


# --------------------------------------------------- compile-count pin
def test_one_compilation_covers_all_batch_mixes(model):
    """The acceptance pin that retires the bucket-recompile axis:
    driving the ragged engine through batch sizes 1..4 (staggered
    arrivals + drains) adds exactly ONE fused_decode_chunk cache entry;
    the bucketed fallback adds one per power-of-two bucket it walks."""
    def drive(kern):
        # num_blocks=28 is used by NO other test: the pool aval is
        # unique to this one, so the jit-cache deltas below count this
        # test's compilations only, whatever ran before
        before = fused_decode_chunk._cache_size()
        # k=2 so requests stay in flight across the staggered arrivals:
        # live counts genuinely walk 1 -> 2 -> 3 -> 4 -> drain, so the
        # bucketed fallback visits buckets 1, 2 AND 4
        eng = _engine(model, kernel=kern, num_blocks=28,
                      decode_chunk_size=2)
        eng.add_request(np.arange(1, 4, dtype=np.int32),
                        SamplingParams(max_tokens=14))
        eng.step()
        for i in range(3):
            eng.add_request(np.arange(2 + i, 7 + i, dtype=np.int32),
                            SamplingParams(max_tokens=12 - 3 * i))
            eng.step()
        eng.run(max_steps=100)
        return fused_decode_chunk._cache_size() - before

    assert drive("ragged") == 1   # THE program: all mixes, one compile
    assert drive("ragged") == 0   # a second engine reuses it
    assert drive("bucketed") == 3  # one per power-of-two bucket walked


def test_padding_waste_gauge(model):
    """3 live rows the whole run: ragged reports 0.0 (fixed width, dead
    rows free), bucketed reports (4-3)/4 from its power-of-two pad."""
    prompts = [np.arange(1, 4, dtype=np.int32)] * 3
    samp = [SamplingParams(max_tokens=6)] * 3
    eng_r, _, _ = _run_engine(model, prompts, samp, kernel="ragged")
    eng_b, _, _ = _run_engine(model, prompts, samp, kernel="bucketed")
    assert eng_r.stats.padding_waste() == 0.0
    assert eng_b.stats.padding_waste() == pytest.approx(0.25)


# ------------------------------------------------------ chunked prefill
def test_chunked_prefill_greedy_matches_dense_prefill(model):
    """Prompts above the threshold stream through the fused scan in
    k-token chunks instead of one-shot generation.prefill; greedy
    output is token-identical to the dense reference (the first token
    comes from in-scan argmax over logits that match the dense
    prefill's row to float32 tolerance — equal argmax, pinned here).
    Short prompts still take the dense path in the same engine."""
    prompts = [np.arange(1, 15, dtype=np.int32),   # chunked (14 > 6)
               np.arange(3, 13, dtype=np.int32),   # chunked (10 > 6)
               np.asarray([9, 1, 7], np.int32)]    # dense   (3 <= 6)
    samp = [SamplingParams(max_tokens=mt) for mt in (8, 10, 6)]
    eng, rids, res = _run_engine(model, prompts, samp, kernel="ragged",
                                 prefill_chunk_threshold=6,
                                 num_blocks=32)
    for rid, p, s in zip(rids, prompts, samp):
        np.testing.assert_array_equal(
            res[rid], _reference_tokens(model, p, s.max_tokens))
    assert eng.stats.prefill_chunks() >= 3   # 14 and 10 tokens at k=8
    eng.cache.check_integrity()


@pytest.mark.parametrize("k", [1, 3, 8])
def test_chunked_prefill_chunk_size_invariant(model, k):
    """The chunked stream does not depend on chunk geometry: feeding a
    prompt 1, 3 or 8 tokens per chunk yields the same output (sampling
    keys are fold_in(seed, progress) — progress-based, so the first
    token's key is identical no matter which trip samples it)."""
    prompts = [np.arange(1, 14, dtype=np.int32),
               np.arange(2, 12, dtype=np.int32)]
    samp = [SamplingParams(max_tokens=7, temperature=0.8, top_k=11,
                           seed=5),
            SamplingParams(max_tokens=7)]
    _, rids, res = _run_engine(model, prompts, samp, kernel="ragged",
                               prefill_chunk_threshold=4,
                               decode_chunk_size=k, num_blocks=32)
    _, rids8, res8 = _run_engine(model, prompts, samp, kernel="ragged",
                                 prefill_chunk_threshold=4,
                                 decode_chunk_size=8, num_blocks=32)
    for r, r8 in zip(rids, rids8):
        np.testing.assert_array_equal(res[r], res8[r8])


def test_eos_mid_chunk_during_chunked_prefill(model):
    """EOS sampled on the very first output of a chunked prompt — the
    trip right after the last fed prompt token, mid-chunk — freezes the
    row in-scan: exactly one token emitted, blocks all returned."""
    p = np.arange(1, 14, dtype=np.int32)
    ref = _reference_tokens(model, p, 4)
    eos = int(ref[0])                     # first output IS the stop
    eng = _engine(model, kernel="ragged", prefill_chunk_threshold=6,
                  num_blocks=32)
    rid = eng.add_request(p, SamplingParams(max_tokens=4,
                                            eos_token_id=eos))
    outs = []
    while eng.has_unfinished():
        outs.extend(eng.step())
    req = eng.get_request(rid)
    np.testing.assert_array_equal(np.asarray(req.output_ids), ref[:1])
    assert outs[-1].finished and outs[-1].finish_reason == "stop"
    assert eng.cache.num_free() == eng.config.num_blocks
    eng.cache.check_integrity()


def test_chunked_prefill_preemption_requeue(model):
    """A pool too small for everyone forces recompute preemption while
    chunked prefills are in flight: the preempted row requeues with its
    pf state reset, re-feeds from the start, and every request still
    completes with the dense-reference tokens — zero leaks."""
    prompts = [np.arange(1, 12, dtype=np.int32),
               np.arange(2, 13, dtype=np.int32),
               np.arange(3, 11, dtype=np.int32)]
    samp = [SamplingParams(max_tokens=mt) for mt in (10, 8, 9)]
    # watermark 1.0 admits everyone off their cheap first chunk (a
    # chunked admission only reserves min(k, ...) slots); the pool then
    # cannot hold all three grown sequences, so growth preempts
    eng, rids, res = _run_engine(model, prompts, samp, kernel="ragged",
                                 prefill_chunk_threshold=4,
                                 num_blocks=10, cache_high_watermark=1.0)
    assert eng.stats.preemptions >= 1
    for rid, p, s in zip(rids, prompts, samp):
        np.testing.assert_array_equal(
            res[rid], _reference_tokens(model, p, s.max_tokens))
    assert eng.cache.num_free() == eng.config.num_blocks
    eng.cache.check_integrity()


def test_chunked_chaos_zero_leak_zero_lost(model):
    """NaN fault lands while a chunked prefill is mid-stream: the
    poisoned chunk is discarded (prefill progress does NOT commit), the
    offender is quarantined, survivors — mid-prefill rows included —
    are rebuilt by requeue and replay bitwise; nothing is lost and no
    block leaks."""
    fi = ServingFaultInjector("nan_logits@2:1")
    eng = LLMEngine.from_model(
        model, EngineConfig(block_size=4, num_blocks=32, max_num_seqs=4,
                            kernel="ragged", prefill_chunk_threshold=4),
        faults=fi)
    prompts = [np.arange(1, 12, dtype=np.int32),
               np.asarray([9, 1, 7, 3, 2, 8, 4, 6, 5], np.int32),
               np.arange(5, 15, dtype=np.int32)]
    rids = [eng.add_request(p, SamplingParams(max_tokens=7))
            for p in prompts]
    res = eng.run(max_steps=200)
    assert ("nan_logits", 2) in fi.fired_log
    states = [eng.get_request(r).state for r in rids]
    assert all(str(s).startswith("finished") for s in states)
    errored = [r for r, s in zip(rids, states) if s == "finished_error"]
    assert len(errored) == 1
    for p, rid in zip(prompts, rids):
        if rid in errored:
            continue
        np.testing.assert_array_equal(
            res[rid], _reference_tokens(model, p, 7))
    assert eng.cache.num_free() == eng.config.num_blocks
    eng.cache.check_integrity()
