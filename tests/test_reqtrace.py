"""Per-request causal tracing (paddle_tpu/obs/reqtrace.py + the
tools/reqtrace.py postmortem CLI).

The load-bearing pins:

- trace-id CONTINUITY: one request = one timeline, across preemption,
  requeue-for-recovery and cross-engine failover (the readmit hop
  carries the same `tr-...` id to the survivor engine);
- the causality checker's invariants hold on real engine runs — no
  token emission before prefill completes, requeue preserves the FCFS
  arrival ticket, exactly-one terminal event per trace, every failover
  hop references a real predecessor — including a 200-request churn
  with cancellations;
- the flight recorder dumps a postmortem artifact on quarantine, and
  `tools/reqtrace.py --check` (run as a subprocess, the CI shape)
  exits 0 on a recorded kill-replica run.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.inference.serving import (EngineConfig, LLMEngine,
                                          ReplicaSet, RouterConfig,
                                          SamplingParams)
from paddle_tpu.obs.reqtrace import ReqTraceRing
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def recording():
    """Fresh, enabled process ring per test; always disarmed after."""
    obs.reqtrace.clear()
    obs.reqtrace.enable()
    yield
    obs.reqtrace.disarm()
    obs.reqtrace.enable()
    obs.reqtrace.clear()


def _engine(model, faults=None, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("max_num_seqs", 4)
    return LLMEngine.from_model(model, EngineConfig(**kw),
                                faults=faults or ServingFaultInjector(""))


def _prompts(n, seed=7, lo=3, hi=8):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, VOCAB, int(rng.randint(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _dump(prefix=None, complete=True, reason="test"):
    """A dump payload over the current ring (optionally one engine's
    traces only), ready for check_causality."""
    ids = None
    if prefix is not None:
        ids = sorted(obs.reqtrace.traces(prefix=prefix))
    return obs.reqtrace.dump_payload(reason, trace_ids=ids,
                                     complete=complete)


def _kinds(evts):
    return [e.kind for e in evts]


# ------------------------------------------------------------- ring unit
def test_ring_bounded_gated_and_closed_catalog():
    r = ReqTraceRing(capacity=4)
    for i in range(10):
        r.record("finish", f"t{i}", reason="stop")
    assert len(r) == 4                       # bounded: oldest dropped
    assert [e.trace_id for e in r.events()] == ["t6", "t7", "t8", "t9"]

    r.enabled = False
    r.record("finish", "t-off", reason="stop")
    assert len(r) == 4                       # disabled = dropped, free
    r.enabled = True

    with pytest.raises(ValueError):
        r.record("not_a_kind", "t0")         # catalog is closed
    r.clear()
    assert len(r) == 0


def test_event_seq_monotonic_and_as_dict_round_trip():
    r = ReqTraceRing()
    r.record("engine_admit", "t0", request_id="r0", engine="e-0")
    r.record("finish", "t0", reason="stop", tokens=3)
    a, b = r.events()
    assert b.seq > a.seq and b.ts >= a.ts
    d = b.as_dict()
    assert d["kind"] == "finish" and d["attrs"]["tokens"] == 3
    assert json.loads(json.dumps(d)) == d    # JSON-safe


# ------------------------------------------------- single-engine timeline
def test_single_engine_timeline_and_checker(model):
    eng = _engine(model)
    rids = [eng.add_request(p, SamplingParams(max_tokens=4))
            for p in _prompts(3)]
    eng.run()

    prefix = f"tr-{eng.stats.label}-"
    traces = obs.reqtrace.traces(prefix=prefix)
    assert len(traces) == 3
    for rid in rids:
        tid = eng.get_request(rid).tid
        ks = _kinds(traces[tid])
        # lifecycle order within one engine's timeline
        for a, b in [("engine_admit", "scheduled"),
                     ("scheduled", "prefill"),
                     ("prefill", "first_token"),
                     ("first_token", "finish")]:
            assert ks.index(a) < ks.index(b), (tid, ks)
        assert ks.count("finish") == 1       # exactly-one terminal
    assert obs.reqtrace.check_causality(_dump(prefix)) == []


def test_ttft_decomposition_components_sane(model):
    eng = _engine(model)
    for p in _prompts(4):
        eng.add_request(p, SamplingParams(max_tokens=3))
    eng.run()
    evts = [e.as_dict() for e in
            obs.reqtrace.events(prefix=f"tr-{eng.stats.label}-")]
    d = obs.reqtrace.ttft_decomposition(evts)
    assert d["n"] == 4
    for k in ("queue_s", "prefill_s", "first_gap_s", "ttft_s"):
        assert d[k] >= 0.0
    # per-trace (not the aggregate — medians of parts don't sum to the
    # median of wholes): admission+queue+prefill+gap == ttft exactly
    for evts_one in obs.reqtrace.traces(
            prefix=f"tr-{eng.stats.label}-").values():
        c = obs.reqtrace.ttft_components(
            [e.as_dict() for e in evts_one])
        assert c is not None
        total = (c["admission_s"] + c["queue_s"] + c["prefill_s"]
                 + c["first_gap_s"])
        assert abs(total - c["ttft_s"]) < 1e-6


# ------------------------------------------------- continuity: preemption
def test_trace_continuity_across_preemption(model):
    # the tight-pool acceptance mix from test_serving.py: at least one
    # preemption, everything completes — each preempted request's
    # preempt/requeue/re-schedule all land on its ONE trace id
    eng = _engine(model, num_blocks=6)
    rng = np.random.RandomState(3)
    lens = [3, 6, 2, 8, 5, 4, 7, 3]
    max_toks = [8, 5, 10, 6, 8, 12, 4, 9]
    rids = []
    for i, (n, mt) in enumerate(zip(lens, max_toks)):
        rids.append(eng.add_request(
            rng.randint(1, VOCAB, (n,)).astype(np.int32),
            SamplingParams(max_tokens=mt)))
        if i % 3 == 2:
            eng.step()
    eng.run()
    assert eng.stats.preemptions >= 1        # pressure actually happened

    prefix = f"tr-{eng.stats.label}-"
    traces = obs.reqtrace.traces(prefix=prefix)
    assert len(traces) == len(rids)          # no id splits or merges
    preempted = [t for t, evts in traces.items()
                 if "preempt" in _kinds(evts)]
    assert preempted
    for tid in preempted:
        ks = _kinds(traces[tid])
        # preempted → re-scheduled on the same timeline, one terminal
        assert ks.index("preempt") < len(ks) - 1 - ks[::-1].index(
            "scheduled")
        assert ks.count("finish") == 1
        # the FCFS ticket is constant across the preemption
        arr = {e.attrs["arrival"] for e in traces[tid]
               if "arrival" in e.attrs}
        assert len(arr) == 1
    assert obs.reqtrace.check_causality(_dump(prefix)) == []


# -------------------------------------------- continuity: kill failover
def test_trace_continuity_across_kill_replica_failover(model, tmp_path):
    faults = ServingFaultInjector("kill_replica@3:1")
    rc = RouterConfig(num_replicas=3, backoff_base=0.01,
                      backoff_max=0.05, backoff_jitter=0.0)
    ecfg = EngineConfig(block_size=4, num_blocks=16, max_num_seqs=4,
                        decode_chunk_size=2)
    rs = ReplicaSet.from_model(model, rc, engine_config=ecfg,
                               faults=faults)
    rids = [rs.add_request(p, SamplingParams(max_tokens=8))
            for p in _prompts(6)]
    rs.run(max_steps=3000)
    assert faults.fired_log, "kill fault never fired"
    assert rs.router_stats()["requeues"] >= 1

    prefix = f"tr-{rs.label}-"
    traces = obs.reqtrace.traces(prefix=prefix)
    assert len(traces) == len(rids)
    victims = [t for t, evts in traces.items()
               if "failover" in _kinds(evts)]
    assert victims
    for tid in victims:
        evts = traces[tid]
        ks = _kinds(evts)
        # ONE timeline spans both engines: admit on the dead replica,
        # failover, readmit (naming the predecessor), finish
        i_fo = ks.index("failover")
        i_re = ks.index("readmit", i_fo)
        assert ks.count("finish") == 1 and ks.index("finish") > i_re
        fo, re_ = evts[i_fo], evts[i_re]
        assert re_.attrs["from_replica"] == fo.attrs["replica"]
        assert re_.attrs["to_replica"] != fo.attrs["replica"]
        # two engine_admit hops, second is the readmit with resumed work
        admits = [e for e in evts if e.kind == "engine_admit"]
        assert len(admits) == 2 and admits[1].attrs["readmit"]
        assert admits[1].attrs["resume"] == fo.attrs["tokens_streamed"]

    dump = _dump(prefix, reason="kill_replica")
    assert obs.reqtrace.check_causality(dump) == []

    # the CI shape: the CLI verifies the same dump in a subprocess
    path = tmp_path / "kill_replica_dump.json"
    path.write_text(json.dumps(dump))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reqtrace.py"),
         str(path), "--check"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 violation(s)" in out.stdout

    # ...and --timeline / --chrome work on the victim's trace
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reqtrace.py"),
         str(path), "--timeline", victims[0],
         "--chrome", str(tmp_path / "tracks.json")],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "failover" in out.stdout and "readmit" in out.stdout
    tracks = json.loads((tmp_path / "tracks.json").read_text())
    assert any(e.get("ph") == "i" and e["name"] == "failover"
               for e in tracks["traceEvents"])


# --------------------------------------------------- churn with cancels
def test_checker_on_200_request_churn_with_cancels(model):
    eng = _engine(model, max_num_seqs=8, num_blocks=48, max_waiting=200)
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, VOCAB, int(rng.randint(3, 6)))
               .astype(np.int32) for _ in range(200)]
    rids, cancelled, submitted = [], set(), 0
    steps = 0
    while submitted < 200 or eng.has_unfinished():
        for _ in range(4):                   # staggered arrivals
            if submitted < 200:
                rids.append(eng.add_request(
                    prompts[submitted], SamplingParams(max_tokens=2)))
                submitted += 1
        if eng.has_unfinished():
            eng.step()
        steps += 1
        if steps % 5 == 0:                   # churn: cancel a live one
            live = [r for r in rids if r not in cancelled
                    and not eng.get_request(r).finished]
            if live:
                victim = live[int(rng.randint(len(live)))]
                eng.cancel(victim)
                cancelled.add(victim)
        assert steps < 4000
    assert submitted == 200 and cancelled

    prefix = f"tr-{eng.stats.label}-"
    traces = obs.reqtrace.traces(prefix=prefix)
    assert len(traces) == 200
    for tid, evts in traces.items():
        ks = _kinds(evts)
        assert ks.count("finish") == 1, (tid, ks)
    reasons = {e.attrs["reason"]
               for r in cancelled
               for e in traces[eng.get_request(r).tid]
               if e.kind == "finish"}
    assert reasons <= {"cancelled"}
    assert obs.reqtrace.check_causality(_dump(prefix)) == []


# ------------------------------------------------ flight recorder: auto
def test_quarantine_auto_flight_dump(model, tmp_path):
    obs.reqtrace.arm(str(tmp_path), max_dumps=2)
    fi = ServingFaultInjector("nan_logits@1")
    eng = _engine(model, faults=fi)
    rids = [eng.add_request(p, SamplingParams(max_tokens=5))
            for p in _prompts(3)]
    eng.run()
    assert eng.stats.errors == 1

    dumps = obs.reqtrace.RING.dumps()
    assert len(dumps) == 1 and "quarantine" in dumps[0]
    dump = json.loads(open(dumps[0]).read())
    assert dump["complete"] is False         # mid-run snapshot
    assert dump["reason"] == "quarantine"
    victim_tid = eng.get_request(rids[0]).tid
    assert victim_tid in dump["trace_ids"]
    ks = [e["kind"] for e in dump["events"]]
    assert "quarantine" in ks
    assert dump["extra"]["why"].startswith("non-finite")
    assert "metrics" in dump["registry"]     # registry snapshot rides
    # the checker tolerates in-flight traces on a complete=False dump
    assert obs.reqtrace.check_causality(dump) == []

    # armed cap: further triggers stop writing files once exhausted
    obs.reqtrace.maybe_flight("failover")
    obs.reqtrace.maybe_flight("failover")
    assert len(obs.reqtrace.RING.dumps()) == 2


def test_checker_flags_violations_on_synthetic_dumps():
    r = ReqTraceRing()
    # token emission before prefill completes
    r.record("engine_admit", "tA", engine="e-9", arrival=0)
    r.record("scheduled", "tA", arrival=0)
    r.record("first_token", "tA")
    r.record("finish", "tA", reason="stop")
    bad = {"version": 1, "complete": True,
           "events": [e.as_dict() for e in r.events()]}
    assert any("prefill" in v for v in
               obs.reqtrace.check_causality(bad))

    # two terminal events
    r.clear()
    r.record("engine_admit", "tB", engine="e-9", arrival=0)
    r.record("scheduled", "tB", arrival=0)
    r.record("prefill", "tB")
    r.record("finish", "tB", reason="stop")
    r.record("finish", "tB", reason="stop")
    bad = {"version": 1, "complete": True,
           "events": [e.as_dict() for e in r.events()]}
    assert any("terminal" in v for v in
               obs.reqtrace.check_causality(bad))

    # missing terminal is OK only when the dump is partial
    r.clear()
    r.record("engine_admit", "tC", engine="e-9", arrival=0)
    partial = {"version": 1, "complete": False,
               "events": [e.as_dict() for e in r.events()]}
    assert obs.reqtrace.check_causality(partial) == []
    full = dict(partial, complete=True)
    assert any("terminal" in v for v in
               obs.reqtrace.check_causality(full))
