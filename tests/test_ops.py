"""Op corpus tests — the OpTest harness analogue.

The reference verifies ~700 ops through one declarative harness
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:232):
check_output runs the op on every place, check_grad compares analytic
gradients against finite differences (get_numeric_gradient:101). Here the
same pattern: outputs vs numpy reference, analytic (tape) grads vs central
finite differences in float64-free f32 with loose tolerance.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x, eps=1e-3):
    """Central finite differences of scalar fn at numpy array x."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        up = fn(x.copy().astype("float32"))
        flat[i] = orig - eps
        dn = fn(x.copy().astype("float32"))
        flat[i] = orig
        gf[i] = (up - dn) / (2 * eps)
    return g


def check_grad(op, x_np, atol=1e-2, rtol=1e-2, **kwargs):
    x = paddle.to_tensor(x_np, stop_gradient=False)
    out = op(x, **kwargs)
    out.sum().backward()

    def scalar_fn(a):
        return float(op(paddle.to_tensor(a), **kwargs).sum().numpy())
    ng = numeric_grad(scalar_fn, x_np.astype(np.float64))
    np.testing.assert_allclose(x.grad.numpy(), ng, atol=atol, rtol=rtol)


UNARY_CASES = [
    (paddle.exp, lambda x: np.exp(x), (2, 3), (-1, 1)),
    (paddle.log, np.log, (2, 3), (0.5, 2)),
    (paddle.sqrt, np.sqrt, (2, 3), (0.5, 2)),
    (paddle.tanh, np.tanh, (2, 3), (-2, 2)),
    (paddle.sin, np.sin, (2, 3), (-2, 2)),
    (paddle.cos, np.cos, (2, 3), (-2, 2)),
    (paddle.square, np.square, (2, 3), (-2, 2)),
    (paddle.abs, np.abs, (2, 3), (0.5, 2)),
    (paddle.sigmoid if hasattr(paddle, "sigmoid") else paddle.tanh,
     lambda x: 1 / (1 + np.exp(-x)) if hasattr(paddle, "sigmoid")
     else np.tanh(x), (2, 3), (-2, 2)),
    (paddle.rsqrt, lambda x: 1 / np.sqrt(x), (2, 3), (0.5, 2)),
    (paddle.log1p, np.log1p, (2, 3), (0.1, 2)),
    (paddle.erf, None, (2, 3), (-1, 1)),
    (paddle.floor, np.floor, (2, 3), (-2, 2)),
    (paddle.reciprocal, lambda x: 1 / x, (2, 3), (0.5, 2)),
]


@pytest.mark.parametrize("op,ref,shape,rng",
                         UNARY_CASES,
                         ids=[c[0].__name__ for c in UNARY_CASES])
def test_unary_output(op, ref, shape, rng):
    x = np.random.uniform(*rng, size=shape).astype("float32")
    out = op(paddle.to_tensor(x)).numpy()
    if ref is not None:
        np.testing.assert_allclose(out, ref(x), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("op", [paddle.exp, paddle.tanh, paddle.sqrt,
                                paddle.log, paddle.square],
                         ids=lambda f: f.__name__)
def test_unary_grad_vs_numeric(op):
    x = np.random.uniform(0.5, 1.5, size=(2, 3)).astype("float32")
    check_grad(op, x)


def test_binary_broadcast_grads():
    a_np = np.random.randn(3, 1, 4).astype("float32")
    b_np = np.random.randn(2, 4).astype("float32")
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np, stop_gradient=False)
    (a * b).sum().backward()
    np.testing.assert_allclose(
        a.grad.numpy(),
        np.broadcast_to(b_np, (3, 2, 4)).sum(1, keepdims=True),
        rtol=1e-5)
    np.testing.assert_allclose(
        b.grad.numpy(),
        np.broadcast_to(a_np, (3, 2, 4)).sum(0),
        rtol=1e-5)


def test_reductions():
    x = np.random.randn(3, 4, 5).astype("float32")
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.sum(t, axis=1).numpy(), x.sum(1),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.mean(t, axis=[0, 2]).numpy(),
                               x.mean((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(paddle.max(t, axis=-1, keepdim=True).numpy(),
                               x.max(-1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(paddle.prod(t, axis=0).numpy(), x.prod(0),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.logsumexp(t, axis=1).numpy(),
                               np.log(np.exp(x).sum(1)), rtol=1e-5)


def test_matmul_variants():
    a = np.random.randn(2, 3, 4).astype("float32")
    b = np.random.randn(2, 4, 5).astype("float32")
    np.testing.assert_allclose(
        paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        a @ b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b.swapaxes(1, 2)),
                      transpose_y=True).numpy(),
        a @ b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.bmm(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        a @ b, rtol=1e-4, atol=1e-5)
    v = np.random.randn(4).astype("float32")
    m = np.random.randn(3, 4).astype("float32")
    np.testing.assert_allclose(paddle.mv(paddle.to_tensor(m),
                                         paddle.to_tensor(v)).numpy(),
                               m @ v, rtol=1e-4, atol=1e-5)


def test_manipulation_roundtrips():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    t = paddle.to_tensor(x)
    assert paddle.reshape(t, [4, 6]).shape == [4, 6]
    assert paddle.reshape(t, [0, -1]).shape == [2, 12]
    assert paddle.transpose(t, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(t, 1, 2).shape == [2, 12]
    assert paddle.unsqueeze(t, [0, -1]).shape == [1, 2, 3, 4, 1]
    assert paddle.squeeze(paddle.ones([1, 2, 1, 3]), axis=0).shape == [2, 1, 3]
    parts = paddle.split(t, [1, 2], axis=1)
    assert parts[0].shape == [2, 1, 4] and parts[1].shape == [2, 2, 4]
    st = paddle.stack([t, t], axis=0)
    assert st.shape == [2, 2, 3, 4]
    assert paddle.tile(paddle.ones([2]), [3, 2]).shape == [3, 4]
    assert paddle.expand(paddle.ones([1, 3]), [4, 3]).shape == [4, 3]
    np.testing.assert_allclose(paddle.flip(t, [0]).numpy(), x[::-1])
    np.testing.assert_allclose(paddle.roll(t, 1, axis=0).numpy(),
                               np.roll(x, 1, axis=0))


def test_gather_scatter():
    x = np.arange(12, dtype="float32").reshape(4, 3)
    idx = np.array([0, 2])
    np.testing.assert_allclose(
        paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx)).numpy(),
        x[idx])
    up = np.ones((2, 3), np.float32)
    out = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx),
                         paddle.to_tensor(up))
    exp = x.copy()
    exp[idx] = up
    np.testing.assert_allclose(out.numpy(), exp)
    nd_idx = np.array([[0, 1], [2, 2]])
    np.testing.assert_allclose(
        paddle.gather_nd(paddle.to_tensor(x),
                         paddle.to_tensor(nd_idx)).numpy(),
        x[[0, 2], [1, 2]])


def test_where_topk_sort():
    x = np.random.randn(3, 5).astype("float32")
    t = paddle.to_tensor(x)
    vals, idx = paddle.topk(t, 2, axis=1)
    np.testing.assert_allclose(vals.numpy(), np.sort(x, 1)[:, ::-1][:, :2],
                               rtol=1e-6)
    np.testing.assert_allclose(paddle.sort(t, axis=1).numpy(), np.sort(x, 1))
    np.testing.assert_allclose(paddle.argsort(t, axis=1).numpy(),
                               np.argsort(x, 1, kind="stable"))
    cond = x > 0
    np.testing.assert_allclose(
        paddle.where(paddle.to_tensor(cond), t, t * 0).numpy(),
        np.where(cond, x, 0))
    assert paddle.argmax(t).numpy() == x.argmax()


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3], dtype="int64").dtype == paddle.int64
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_allclose(paddle.arange(1, 7, 2).numpy(), [1, 3, 5])
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                               np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
    u = paddle.uniform([100], min=0, max=1)
    assert 0 <= float(u.numpy().min()) and float(u.numpy().max()) <= 1
    r = paddle.randint(0, 10, [50])
    assert r.numpy().min() >= 0 and r.numpy().max() < 10
    assert sorted(paddle.randperm(10).numpy().tolist()) == list(range(10))
    np.testing.assert_allclose(paddle.tril(paddle.ones([3, 3])).numpy(),
                               np.tril(np.ones((3, 3))))


def test_linalg_extras():
    a = np.random.randn(4, 4).astype("float32")
    spd = a @ a.T + 4 * np.eye(4, dtype="float32")
    t = paddle.to_tensor(spd)
    L = paddle.cholesky(t).numpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        paddle.inverse(t).numpy() @ spd, np.eye(4), atol=1e-3)
    np.testing.assert_allclose(paddle.ops.linalg.det(t).numpy(),
                               np.linalg.det(spd), rtol=1e-3)
    n = paddle.ops.linalg.norm(paddle.to_tensor(a))
    np.testing.assert_allclose(n.numpy(), np.linalg.norm(a), rtol=1e-5)
    e = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(a))
    np.testing.assert_allclose(e.numpy(), a @ a, rtol=1e-4, atol=1e-4)


def test_logic_ops():
    a = paddle.to_tensor([1, 2, 3])
    b = paddle.to_tensor([1, 0, 3])
    np.testing.assert_array_equal((a == b).numpy(), [True, False, True])
    np.testing.assert_array_equal((a > b).numpy(), [False, True, False])
    assert bool(paddle.allclose(paddle.ones([2]), paddle.ones([2])).numpy())
    assert bool(paddle.ops.logic.equal_all(a, a).numpy())
    assert not bool(paddle.ops.logic.equal_all(a, b).numpy())


def test_cast_and_dtypes():
    x = paddle.ones([2], dtype="float32")
    assert x.astype("int64").dtype == paddle.int64
    assert x.astype(paddle.bfloat16).dtype == paddle.bfloat16
    assert paddle.get_default_dtype() == paddle.float32


def test_cumsum_clip_lerp():
    x = np.random.randn(3, 4).astype("float32")
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.cumsum(t, axis=1).numpy(),
                               np.cumsum(x, 1), rtol=1e-5)
    np.testing.assert_allclose(paddle.clip(t, -0.5, 0.5).numpy(),
                               np.clip(x, -0.5, 0.5))
    np.testing.assert_allclose(
        paddle.ops.math.lerp(paddle.zeros([3]), paddle.ones([3]), 0.3).numpy(),
        np.full(3, 0.3), rtol=1e-6)
