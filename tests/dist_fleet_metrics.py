"""Per-rank half of an eval set; fleet metrics must equal the single-rank
metric over the union (reference fleet/metrics contract)."""
import json
import os

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402

N_BUCKETS = 256


def full_data():
    rs = np.random.RandomState(7)
    scores = rs.rand(400)
    labels = (rs.rand(400) < scores * 0.8).astype(np.int64)  # correlated
    preds = (scores > 0.5).astype(np.int64)
    return scores, labels, preds


def stats(scores, labels, preds):
    buckets = np.minimum((scores * N_BUCKETS).astype(int), N_BUCKETS - 1)
    pos = np.bincount(buckets[labels == 1], minlength=N_BUCKETS)
    neg = np.bincount(buckets[labels == 0], minlength=N_BUCKETS)
    correct = float((preds == labels).sum())
    total = float(len(labels))
    abserr = np.abs(scores - labels).sum()
    sqrerr = ((scores - labels) ** 2).sum()
    return pos, neg, correct, total, abserr, sqrerr


def main():
    env = paddle.distributed.init_parallel_env()
    r, w = env.rank, env.world_size
    scores, labels, preds = full_data()
    per = len(scores) // w
    sl = slice(r * per, (r + 1) * per)
    pos, neg, correct, total, abserr, sqrerr = stats(
        scores[sl], labels[sl], preds[sl])
    rec = {
        "rank": r,
        "auc": fleet.metrics.auc(pos, neg),
        "acc": fleet.metrics.acc(correct, total),
        "mae": fleet.metrics.mae(abserr, total),
        "rmse": fleet.metrics.rmse(sqrerr, total),
        "sum": float(fleet.metrics.sum(np.asarray([correct]))[0]),
    }
    out_dir = os.environ.get("DIST_OUT_DIR")
    path = os.path.join(out_dir, f"rank{r}.json")
    with open(path + ".tmp", "w") as f:
        json.dump(rec, f)
    os.replace(path + ".tmp", path)


if __name__ == "__main__":
    main()
