"""Supervised training worker for tests/test_fault_tolerance.py.

Trains a deterministic linear regression for FT_TOTAL_STEPS steps under an
AutoCheckpointManager in step-granular mode, with env-driven fault
injection (PADDLE_TPU_FAULTS). Every batch is a pure function of the step
index, so a killed-and-resumed run MUST reach bitwise-identical final
parameters to an uninterrupted one — any divergence is a checkpoint/restore
bug, not test noise.

Env contract:
    FT_CKPT_DIR          checkpoint directory (shared across restarts)
    FT_OUT               result JSON path (written atomically at the end)
    FT_TOTAL_STEPS       default 12
    FT_SAVE_EVERY        default 4
    FT_ANOMALY_POLICY    optional: raise | skip_step | zero_grads
plus the supervisor's PADDLE_ELASTIC_* vars and the fault-injector's
PADDLE_TPU_FAULTS / PADDLE_TPU_FAULT_STATE_DIR.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
from paddle_tpu.core import anomaly  # noqa: E402
from paddle_tpu.incubate.checkpoint import AutoCheckpointManager  # noqa: E402
from paddle_tpu.testing.faults import FaultInjector  # noqa: E402


def batch(step):
    """Deterministic per-step data: replaying a step after restore sees
    exactly the bytes the killed incarnation saw."""
    rs = np.random.RandomState(1000 + step)
    X = rs.randn(8, 4).astype("float32")
    Y = rs.randn(8, 2).astype("float32")
    return X, Y


def main():
    ckpt_dir = os.environ["FT_CKPT_DIR"]
    out_path = os.environ["FT_OUT"]
    total = int(os.environ.get("FT_TOTAL_STEPS", "12"))
    save_every = int(os.environ.get("FT_SAVE_EVERY", "4"))
    policy = os.environ.get("FT_ANOMALY_POLICY")

    with paddle.utils.unique_name.guard():
        paddle.seed(7)
        model = paddle.nn.Linear(4, 2)
        optim = opt.Adam(1e-2, parameters=model.parameters())

    guard = anomaly.set_anomaly_guard(policy) if policy else None
    inj = FaultInjector()  # env-driven; inert without PADDLE_TPU_FAULTS
    acp = AutoCheckpointManager(ckpt_dir, models=[model], optimizers=[optim],
                                save_every_n_steps=save_every)

    steps_run = []
    for step in acp.train_step_range(total):
        inj.step(step, checkpoint_dir=ckpt_dir)
        X, Y = batch(step)
        loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss = inj.poison_loss(step, loss)
        loss.backward()
        optim.step()
        optim.clear_grad()
        steps_run.append(step)

    result = {
        "params": {k: np.asarray(v.numpy()).tolist()
                   for k, v in model.state_dict().items()},
        "first_step": steps_run[0] if steps_run else None,
        "steps_run": len(steps_run),
        "restart_count": int(os.environ.get("PADDLE_ELASTIC_RESTART_COUNT",
                                            "0")),
        "anomaly": guard.state_dict() if guard else None,
        "quarantined": sorted(n for n in os.listdir(ckpt_dir)
                              if n.endswith(".corrupt")),
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.rename(tmp, out_path)  # atomic: the test never reads a torn file


if __name__ == "__main__":
    sys.exit(main())
