"""Tier-1 tests for the lockgraph suite (docs/static_analysis.md).

Three layers, mirroring the check itself:

  1. analyzer mechanics — order inversions, undeclared locks, cycles,
     transitive acquisition inference, and the propagation boundary
     (a blocking callee is reported at the locked call site, not at
     every caller above it);
  2. CLI gate — `tools/lockgraph.py --check` exit-code semantics
     (0 clean / 1 findings / 2 model+parse errors), suppressions with
     reasons, and the repo-wide zero-unsuppressed acceptance gate;
  3. runtime witness — locktrace records real acquisition edges,
     reentrancy is edge-free, the witnessed graph cycle-checks, and
     cross-validation flags a seeded edge the static DAG never
     predicted (the analyzer-rot tripwire).

The PT-C002/3/4 fixture corpus in tests/data/ptlint/ is exercised by
test_static_analysis.py's parametrized fixture runner.
"""
import json
import pathlib
import subprocess
import sys
import textwrap
import threading

import pytest

from paddle_tpu.analysis import lockgraph as lg
from paddle_tpu.analysis.lockgraph import (LockGraphProgram, LockModel,
                                           _find_cycles)
from paddle_tpu.testing.locktrace import LockWitness, TracedLock

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
CLI = REPO / "tools" / "lockgraph.py"
FIXDIR = REPO / "tests" / "data" / "ptlint"


def _analyze(src, order=()):
    prog = LockGraphProgram()
    prog.add_module("mod.py", textwrap.dedent(src))
    model = LockModel(order=list(order))
    return prog.analyze(model), prog, model


# ---------------------------------------------------- analyzer mechanics
_TWO_LOCKS = """
import threading


class Outer:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self):
        with self._lock:
            pass


class Inner:
    def __init__(self, outer: Outer):
        self._lock = threading.Lock()
        self.outer = outer

    def bad(self):
        with self._lock:
            self.outer.flush()
"""


def test_order_inversion_is_found():
    findings, _, _ = _analyze(_TWO_LOCKS,
                              order=["Outer._lock", "Inner._lock"])
    assert [f.rule for f in findings] == ["PT-C002"]
    assert "INVERTS" in findings[0].message


def test_conforming_order_is_clean():
    findings, _, _ = _analyze(_TWO_LOCKS,
                              order=["Inner._lock", "Outer._lock"])
    assert not findings


def test_undeclared_lock_is_a_finding():
    findings, _, _ = _analyze(_TWO_LOCKS, order=["Inner._lock"])
    assert findings
    assert "not in the declared lock order" in findings[0].message


_CYCLE = """
import threading


class A:
    def __init__(self):
        self._lock = threading.Lock()

    def lockme(self):
        with self._lock:
            pass

    def a_then_b(self, b: B):
        with self._lock:
            b.lockme()


class B:
    def __init__(self):
        self._lock = threading.Lock()

    def lockme(self):
        with self._lock:
            pass

    def b_then_a(self, a: A):
        with self._lock:
            a.lockme()
"""


def test_cycle_is_found():
    findings, _, _ = _analyze(_CYCLE, order=["A._lock", "B._lock"])
    assert any("cycle" in f.message for f in findings)
    # the inverted direction is also called out on its own line
    assert any("INVERTS" in f.message for f in findings)


_TRANSITIVE = """
import threading


class Deep:
    def __init__(self):
        self._lock = threading.Lock()

    def leaf(self):
        with self._lock:
            pass

    def mid(self):
        self.leaf()


class Top:
    def __init__(self, deep: Deep):
        self._lock = threading.Lock()
        self.deep = deep

    def entry(self):
        with self._lock:
            self.deep.mid()
"""


def test_transitive_acquisition_inference():
    findings, prog, model = _analyze(
        _TRANSITIVE, order=["Top._lock", "Deep._lock"])
    assert not findings
    # Deep.mid acquires Deep._lock only through leaf(), yet the edge
    # from Top's locked call is still inferred
    assert "Deep._lock" in prog.summaries[("Deep", "mid")].enters
    assert ("Top._lock", "Deep._lock") in {
        (h, a) for (h, a, *_rest) in prog.edges(model)}


_BLOCKING = """
import threading
import time


class W:
    def __init__(self):
        self._lock = threading.Lock()

    def _slow(self):
        time.sleep(0.5)

    def locked_entry(self):
        with self._lock:
            self._slow()

    def outer(self):
        self.locked_entry()
"""


def test_blocking_reported_at_locked_call_site_only():
    """The finding lands where the lock meets the blocking callee —
    it is NOT propagated to every caller further up the stack."""
    findings, _, _ = _analyze(_BLOCKING, order=["W._lock"])
    assert len(findings) == 1
    assert findings[0].rule == "PT-C003"
    assert "_slow" in findings[0].message


# ------------------------------------------------------------- CLI gate
def _cli(*args):
    return subprocess.run([sys.executable, str(CLI), *args],
                          capture_output=True, text=True, cwd=str(REPO))


def test_cli_repo_check_is_clean():
    """Acceptance gate: zero unsuppressed findings over the fleet."""
    res = _cli("--check")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "clean" in res.stdout


def test_cli_findings_exit_one_and_name_the_rule():
    res = _cli(str(FIXDIR / "c003_tp.py"))
    assert res.returncode == 1
    assert "PT-C003" in res.stdout


def test_cli_json_output_is_parseable():
    res = _cli("--format", "json", str(FIXDIR / "c002_tp.py"))
    assert res.returncode == 1
    payload = json.loads(res.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"PT-C002"}
    assert payload["order"], "committed model carries a declared order"


def test_cli_suppression_with_reason_silences(tmp_path):
    bad = tmp_path / "w.py"
    bad.write_text(textwrap.dedent("""
        import threading
        import time


        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    time.sleep(0.01)  # ptlint: disable=PT-C003  fixture
        """))
    res = _cli(str(bad))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "1 suppressed" in res.stdout


def test_cli_bad_model_exits_two():
    res = _cli("--model", "/nonexistent/lockgraph.json")
    assert res.returncode == 2


def test_cli_parse_error_exits_two(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    res = _cli(str(bad))
    assert res.returncode == 2


# ------------------------------------------------------ runtime witness
def _nest(witness, names):
    """Acquire the named locks in order, release in reverse."""
    locks = [TracedLock(n, threading.Lock(), witness) for n in names]
    for lk in locks:
        lk.acquire()
    for lk in reversed(locks):
        lk.release()


def test_witness_records_edges_and_cycle_checks():
    w = LockWitness()
    _nest(w, ["A._lock", "B._lock"])
    assert w.edges() == {("A._lock", "B._lock")}
    assert w.cycle_check() == []
    assert w.cross_validate({("A._lock", "B._lock")}) == []
    # the opposite interleaving closes a cycle
    _nest(w, ["B._lock", "A._lock"])
    assert w.cycle_check()


def test_witness_reentrancy_is_edge_free():
    w = LockWitness()
    lk = TracedLock("R._lock", threading.RLock(), w)
    with lk:
        with lk:
            pass
    assert w.edges() == set()
    assert w.acquisitions == 1
    assert len(w.span_list()) == 2


def test_predicted_edges_are_acyclic_and_cover_the_fleet():
    predicted = lg.predicted_edges(str(REPO))
    assert ("ReplicaSet._lock", "LLMEngine._lock") in predicted
    assert ("LLMEngine._lock", "Scheduler._lock") in predicted
    assert _find_cycles(predicted) == []


def test_seeded_unpredicted_edge_fails_cross_validation():
    """The analyzer-rot tripwire: a witnessed edge the static DAG never
    predicted (here the seeded inversion Scheduler -> ReplicaSet) must
    surface as a cross-validation failure."""
    predicted = lg.predicted_edges(str(REPO))
    w = LockWitness()
    _nest(w, ["ReplicaSet._lock", "Scheduler._lock"])   # predicted
    _nest(w, ["Scheduler._lock", "ReplicaSet._lock"])   # seeded rogue
    assert w.cross_validate(predicted) == [
        ("Scheduler._lock", "ReplicaSet._lock")]
    rep = w.report(predicted)
    assert rep["unpredicted_edges"] == [
        ["Scheduler._lock", "ReplicaSet._lock"]]
