"""Round-5 correctness fixes (ADVICE round 5 items).

Oracles: numpy put-along-axis accumulation loops and numpy
maximum.accumulate / argmax semantics, each run with NEGATIVE axis values
— the configurations that previously crashed (cummax: lax reject) or
silently scattered along the wrong dimension (put_along_axis reduce=).
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle


def _np_put_along_axis(arr, idx, vals, axis, reduce):
    out = arr.copy()
    vals = np.broadcast_to(vals, idx.shape)
    for pos in np.ndindex(*idx.shape):
        dest = list(pos)
        dest[axis] = idx[pos]
        dest = tuple(dest)
        if reduce == "assign":
            out[dest] = vals[pos]
        elif reduce == "add":
            out[dest] += vals[pos]
        elif reduce == "mul":
            out[dest] *= vals[pos]
    return out


@pytest.mark.parametrize("reduce", ["assign", "add", "mul"])
@pytest.mark.parametrize("axis", [-1, -2])
def test_put_along_axis_negative_axis(reduce, axis):
    """axis=-1 with reduce='add'/'mul' previously built the scatter
    dnums for a shifted dimension (ADVICE round 5 high)."""
    rng = np.random.RandomState(5)
    arr = rng.rand(3, 4).astype("float32")
    idx = rng.randint(0, arr.shape[axis], size=(3, 2)).astype("int64")
    if axis == -2:
        idx = rng.randint(0, 3, size=(2, 4)).astype("int64")
    vals = rng.rand(*idx.shape).astype("float32")

    got = paddle.put_along_axis(paddle.to_tensor(arr), paddle.to_tensor(idx),
                                paddle.to_tensor(vals), axis, reduce=reduce)
    want = _np_put_along_axis(arr, idx, vals, axis + arr.ndim, reduce)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-6)

    # negative axis must agree exactly with its positive alias
    got_pos = paddle.put_along_axis(
        paddle.to_tensor(arr), paddle.to_tensor(idx), paddle.to_tensor(vals),
        axis + arr.ndim, reduce=reduce)
    np.testing.assert_array_equal(got.numpy(), got_pos.numpy())


@pytest.mark.parametrize("axis", [-1, -2])
def test_cummax_negative_axis(axis):
    """cummax(axis=-1) previously crashed: lax.cummax rejects negative
    axes and the index-grid reshape never matched them (ADVICE round 5)."""
    rng = np.random.RandomState(7)
    x = rng.rand(3, 4, 5).astype("float32")
    out, idx = paddle.cummax(paddle.to_tensor(x), axis=axis)
    np.testing.assert_allclose(out.numpy(),
                               np.maximum.accumulate(x, axis=axis), rtol=1e-6)
    pos_out, pos_idx = paddle.cummax(paddle.to_tensor(x), axis=axis + x.ndim)
    np.testing.assert_array_equal(out.numpy(), pos_out.numpy())
    np.testing.assert_array_equal(idx.numpy(), pos_idx.numpy())
    # indices index along the cummax axis: gathering with them rebuilds out
    take = np.take_along_axis(x, idx.numpy().astype("int64"), axis=axis)
    np.testing.assert_allclose(take, out.numpy(), rtol=1e-6)


def test_scaling_anchor_reads_bench_detail(tmp_path):
    """ADVICE round 5: the projection anchor must read the headline's
    `value` key (and verify the metric name), not a metric-named key."""
    import sys
    sys.path.insert(0, "tools")
    try:
        from scaling_analysis import FLAGSHIP_METRIC, read_flagship_anchor
    finally:
        sys.path.pop(0)

    # live headline → anchor derived from it, labeled live
    (tmp_path / "BENCH_DETAIL.json").write_text(json.dumps(
        {"metric": FLAGSHIP_METRIC, "value": 163840.0}))
    step_s, src = read_flagship_anchor(str(tmp_path))
    assert step_s == pytest.approx(32 * 1024 / 163840.0, abs=1e-4)
    assert "live" in src

    # wrong metric (re-pointed headline) → raises LOUDLY; before the REVIEW
    # fix this ValueError was swallowed by the function's own except and
    # silently pinned the fallback
    (tmp_path / "BENCH_DETAIL.json").write_text(json.dumps(
        {"metric": "resnet_imgs_per_sec", "value": 9999.0}))
    with pytest.raises(ValueError, match="headline metric"):
        read_flagship_anchor(str(tmp_path))

    # right metric but malformed value → also loud, not fallback
    (tmp_path / "BENCH_DETAIL.json").write_text(json.dumps(
        {"metric": FLAGSHIP_METRIC}))
    with pytest.raises(KeyError):
        read_flagship_anchor(str(tmp_path))

    # missing file → fallback (the only silent path left)
    step_s, src = read_flagship_anchor(str(tmp_path / "nope"))
    assert step_s == 0.1996 and "fallback" in src
