"""Go inference binding (go/paddle over native/src/pd_capi.cc).

Reference: go/paddle/{config,predictor,tensor}.go — re-authored for this
framework's PD_* C surface. The full smoke (go build + run against a
saved model) needs a Go toolchain; when `go` is absent the build test
skips and the structural checks still run.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GO_DIR = os.path.join(REPO, "go")


def test_go_sources_bind_every_capi_symbol():
    """Every exported PD_* function in pd_capi.cc is dlsym'd by the Go
    binding (the binding cannot silently drift from the C surface)."""
    import re
    capi = open(os.path.join(
        REPO, "paddle_tpu", "native", "src", "pd_capi.cc")).read()
    exported = set(re.findall(r"\b(PD_\w+)\s*\(", capi))
    exported = {n for n in exported if not n.startswith("PD_Get_")}
    go_src = open(os.path.join(GO_DIR, "paddle", "predictor.go")).read()
    missing = [n for n in sorted(exported) if f'"{n}"' not in go_src]
    assert not missing, f"Go binding misses C API symbols: {missing}"


def test_go_smoke_builds_and_runs(tmp_path):
    """End-to-end: save an inference model, go run the smoke binary
    against the built _pd_capi.so, assert the output marker."""
    go = shutil.which("go")
    if go is None:
        pytest.skip("go toolchain not available in this image")

    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from paddle_tpu.native import capi_so_path

    paddle.enable_static()
    try:
        with paddle.utils.unique_name.guard():
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [1, 4], "float32")
                out = static.nn.fc(x, 2)
            exe = static.Executor()
            exe.run(startup)
            static.save_inference_model(str(tmp_path / "model"), [x],
                                        [out], exe, main)
    finally:
        paddle.disable_static()

    env = dict(os.environ)
    env["PD_CAPI_LIB"] = capi_so_path()
    env["CGO_ENABLED"] = "1"
    res = subprocess.run(
        [go, "run", "./smoke", str(tmp_path / "model"), "1,4"],
        cwd=GO_DIR, env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "GO_SMOKE_OK" in res.stdout
