"""bench.py smoke: the driver's round-end artifact must stay runnable.

Every workload's CPU-sized variant runs one tiny window and returns a
positive rate — catches import errors, signature drift between bench.py
and the models/jit APIs, and broken BENCH_FULL sub-benches before the
driver (or a judge) hits them on the real chip. Marked slow: ~2-3 min
of tiny compiles on the CPU mesh.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402

pytestmark = pytest.mark.slow


def test_gpt_headline_cpu():
    tps, mfu = bench.bench_gpt(False)
    assert tps > 0
    assert mfu is None  # MFU only reported on the chip


def test_full_subbenches_cpu():
    assert bench.bench_lenet(False) > 0
    assert bench.bench_lenet_multistep(False) > 0
    bt, _ = bench.bench_bert(False)
    assert bt > 0
    er, _, er_bs = bench.bench_ernie(False)
    assert er > 0 and er_bs == 2  # CPU smoke geometry
    rn, _ = bench.bench_resnet(False)
    assert rn > 0
    dc, _ = bench.bench_decode(False)
    assert dc > 0
    sd, sd_detail = bench.bench_serve_decode(False)
    assert sd > 0
    assert sd_detail["generated_tokens"] > 0
    assert sd_detail["steps"] > 0


def test_chaos_serve_runner_cpu():
    """tools/chaos_serve.py smoke: a short seeded fault schedule drains
    with zero leaked blocks and bitwise-clean survivors (exit 0)."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import chaos_serve
    rc = chaos_serve.main(["--seed", "1", "--requests", "8",
                           "--faults", "nan_logits@3,stall@5:0.05"])
    assert rc == 0
