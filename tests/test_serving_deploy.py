"""Multi-model fleet + chaos-gated rolling weight deploys
(paddle_tpu/inference/serving/deploy.py, router.py, migration.py).

The load-bearing pins (docs/serving.md "Multi-model serving and
rolling deploys"):

- publishing is content-addressed by the sha256 checkpoint manifest:
  identical weights republish as the SAME revision (no new lineage
  entry), drifted weights as a different one, and an artifact with no
  `checksums.json` is a hard publish error;
- a rolling deploy under live traffic commits replica-by-replica with
  zero lost requests, flips the registry-active revision, and clears
  the A/B route weights;
- requests that stay pinned to the OLD revision finish bitwise against
  a no-deploy run on old weights;
- a poisoned candidate revision is caught by the canary parity gate at
  the committed tolerance and rolled back atomically — old revision
  still active, every replica restored, nothing lost;
- a kill inside the swap->canary window after an earlier slot already
  rejoined rolls back the LIVE slot too, through the router's
  zero-lost eviction;
- KV never crosses revisions: the migrator refuses both live-request
  migration and peer prefix pulls between replicas with different
  (model, revision) keys;
- reqtrace invariant 8 (no token under a revision other than the
  admitted one) and the deploy-trace terminal rule (exactly one
  commit XOR rollback per started deploy) hold on real runs and flag
  synthetic violations.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.inference.serving import (DeployConfig, DeployController,
                                          EngineConfig, ModelRegistry,
                                          ReplicaSet, ReplicaState,
                                          RouterConfig, SamplingParams)
from paddle_tpu.obs.reqtrace import ReqTraceRing
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97


def _gpt(seed):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _gpt(0)


@pytest.fixture(scope="module")
def model_b():
    # genuinely different weights: the canary gate sees real greedy
    # divergence, so a clean deploy must COMMIT its tolerance
    return _gpt(1)


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("decode_chunk_size", 2)   # keep requests in flight
    return EngineConfig(**kw)


def _registry(old, new):
    reg = ModelRegistry()
    r0 = reg.publish("m", old, engine_config=_ecfg())
    r1 = reg.publish("m", new, engine_config=_ecfg())
    assert r0 != r1
    return reg, r0, r1


def _fleet(reg, n=2, faults=None, **rkw):
    rkw.setdefault("backoff_base", 0.01)
    rkw.setdefault("backoff_max", 0.05)
    rkw.setdefault("backoff_jitter", 0.0)
    return ReplicaSet.from_registry(
        reg, ("m",) * n, config=RouterConfig(num_replicas=n, **rkw),
        faults=faults or ServingFaultInjector(""))


def _prompts(n, seed=7, lo=3, hi=8):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, VOCAB, int(rng.randint(lo, hi))).astype(np.int32)
            for _ in range(n)]


def _sp(mt=6):
    return SamplingParams(max_tokens=mt, model="m")


def _assert_no_leaks(rs):
    for idx, audit in rs.check_integrity().items():
        assert audit is not None, f"replica {idx} has no live engine"
        assert audit["leaked"] == 0, (idx, audit)


def _assert_all_served(rs, rids):
    for rid in rids:
        rec = rs.get_request(rid)
        assert rec.finished, rid
        assert rec.finish_reason in ("stop", "length"), \
            (rid, rec.finish_reason)


# ------------------------------------------------------------- registry
def test_publish_is_content_addressed_and_idempotent(model, model_b):
    reg = ModelRegistry()
    r0 = reg.publish("m", model)
    assert r0.startswith("sha256:") and len(r0) == len("sha256:") + 12
    # identical weights -> same id, no new lineage entry
    assert reg.publish("m", model) == r0
    assert reg.revisions("m") == (r0,)
    assert reg.active("m") == r0              # first publish activates
    r1 = reg.publish("m", model_b)
    assert r1 != r0                           # drifted weights, new id
    assert reg.revisions("m") == (r0, r1)     # publish-ordered lineage
    assert reg.active("m") == r0              # later publishes do NOT
    reg.set_active("m", r1)
    assert reg.active("m") == r1
    desc = reg.describe()
    assert desc["m"] == {"revisions": [r0, r1], "active": r1}
    with pytest.raises(ValueError, match="unknown model"):
        reg.active("ghost")
    with pytest.raises(ValueError, match="no revision"):
        reg.set_active("m", "sha256:000000000000")


def test_publish_artifact_requires_manifest(tmp_path, model):
    reg = ModelRegistry()
    art = tmp_path / "artifact"
    art.mkdir()
    with pytest.raises(IOError, match="checksums.json"):
        reg.publish("m", model, artifact_dir=str(art))
    assert not reg.has_model("m")             # nothing half-published
    (art / "checksums.json").write_text("[]")
    with pytest.raises(IOError, match="empty or malformed"):
        reg.publish("m", model, artifact_dir=str(art))
    manifest = {"layer0/w": "ab" * 32, "layer0/b": "cd" * 32}
    (art / "checksums.json").write_text(json.dumps(manifest))
    rid = reg.publish("m", model, artifact_dir=str(art))
    assert rid.startswith("sha256:")
    assert reg.manifest("m", rid) == manifest


def test_registry_engines_are_revision_stamped(model, model_b):
    reg, r0, r1 = _registry(model, model_b)
    eng = reg.build_engine("m", None, 0, 0)   # None -> active revision
    assert (eng.config.model, eng.config.revision) == ("m", r0)
    # the pinned factory builds the requested revision, not the active
    eng2 = reg.engine_factory("m", r1)(3, 0)
    assert (eng2.config.model, eng2.config.revision) == ("m", r1)
    with pytest.raises(ValueError, match="no revision"):
        reg.build_engine("m", "sha256:000000000000", 0, 0)
    with pytest.raises(ValueError, match="unknown model"):
        reg.engine_factory("ghost", r0)


def test_controller_preconditions(model, model_b):
    reg, r0, r1 = _registry(model, model_b)
    plain = ReplicaSet.from_model(
        model, RouterConfig(num_replicas=1), engine_config=_ecfg())
    with pytest.raises(ValueError, match="ModelRegistry"):
        DeployController(plain, "m", r1)
    rs = _fleet(reg, n=1)
    with pytest.raises(ValueError, match="already at"):
        DeployController(rs, "m", r0)         # no-op deploy
    with pytest.raises(ValueError, match="no revision"):
        DeployController(rs, "m", "sha256:000000000000")


# -------------------------------------------------------------- commits
def test_rolling_deploy_commits_under_live_traffic(model, model_b):
    reg, r0, r1 = _registry(model, model_b)
    rs = _fleet(reg, n=2)
    rids = [rs.add_request(p, _sp()) for p in _prompts(6)]
    # the candidate genuinely diverges on every canary prompt; the
    # committed tolerance covering the full set is what lets it ship
    ctl = DeployController(rs, "m", r1,
                           config=DeployConfig(canary_tolerance=3))
    ctl.start()
    while not ctl.done():
        rs.step()
        ctl.tick()
    rs.run(max_steps=2000)

    st = ctl.status()
    assert st["outcome"] == "committed", st
    assert st["error"] is None
    assert st["swapped"] == [0, 1]
    assert reg.active("m") == r1              # registry flipped
    for rep in rs.replicas:
        assert rep.revision == r1
        assert rep.is_serving()
    assert rs.route_weights("m") == {}        # A/B split cleared
    _assert_all_served(rs, rids)              # zero lost
    _assert_no_leaks(rs)

    # post-commit traffic is admitted under the new revision
    rid = rs.add_request(np.arange(1, 5, dtype=np.int32), _sp(mt=3))
    assert rs.get_request(rid).revision == r1
    rs.run(max_steps=500)
    _assert_no_leaks(rs)

    # the deploy trace on the closed catalog: start, one swap + canary
    # per slot, exactly one commit — and the merged request + deploy
    # timeline passes the checker (invariant 8 included)
    dep = [e.kind for e in obs.reqtrace.events(trace_id=ctl.deploy_id)]
    assert dep[0] == "deploy_start"
    assert dep.count("replica_swap") == 2
    assert dep.count("canary") == 2
    assert dep[-1] == "deploy_commit"
    ids = sorted(obs.reqtrace.traces(prefix=ctl.deploy_id))
    ids += sorted(obs.reqtrace.traces(prefix=f"tr-{rs.label}-"))
    dump = obs.reqtrace.dump_payload("deploy-commit-test",
                                     trace_ids=ids, complete=True)
    assert obs.reqtrace.check_causality(dump) == []


def test_old_revision_requests_finish_bitwise(model, model_b):
    prompts = _prompts(5, seed=11)
    # reference: the same prompts on an old-revision fleet, no deploy
    ref = _fleet(_registry(model, model_b)[0], n=2)
    ref_rids = [ref.add_request(p, _sp()) for p in prompts]
    ref.run(max_steps=2000)
    want = [list(ref.get_request(r).tokens) for r in ref_rids]
    _assert_no_leaks(ref)

    reg, r0, r1 = _registry(model, model_b)
    rs = _fleet(reg, n=2)
    rids = [rs.add_request(p, _sp()) for p in prompts]
    for _ in range(3):                        # work underway pre-deploy
        rs.step()
    DeployController(rs, "m", r1,
                     config=DeployConfig(canary_tolerance=3)).run()
    rs.run(max_steps=2000)
    _assert_all_served(rs, rids)
    # every request that FINISHED pinned to the old revision matched
    # the no-deploy run token-for-token (re-pinned ones re-prefilled
    # on new weights and legitimately drifted)
    checked = 0
    for i, rid in enumerate(rids):
        rec = rs.get_request(rid)
        if rec.revision == r0:
            assert list(rec.tokens) == want[i], i
            checked += 1
    assert checked >= 1                       # the gate was not vacuous
    _assert_no_leaks(rs)


# ------------------------------------------------------------ rollbacks
def test_poisoned_revision_rolls_back(model, model_b):
    reg, r0, r_bad = _registry(model, model_b)
    rs = _fleet(reg, n=2)
    rids = [rs.add_request(p, _sp(mt=5)) for p in _prompts(4)]
    # strict default tolerance 0: the divergent candidate must abort
    ctl = DeployController(rs, "m", r_bad)
    st = ctl.run()
    rs.run(max_steps=2000)

    assert st["outcome"] == "rolled_back", st
    assert "canary" in st["error"] and "diverged" in st["error"]
    assert reg.active("m") == r0              # old revision still live
    for rep in rs.replicas:
        assert rep.revision == r0             # warm engines restored
        assert rep.is_serving()
    assert rs.route_weights("m") == {}
    _assert_all_served(rs, rids)              # in-flight work survived
    _assert_no_leaks(rs)
    # rollback released the warm standby path: the slot still restarts
    rid = rs.add_request(np.arange(1, 6, dtype=np.int32), _sp(mt=3))
    assert rs.get_request(rid).revision == r0
    rs.run(max_steps=500)
    _assert_no_leaks(rs)


def test_rollback_unwinds_live_swapped_slot(model, model_b):
    # kill_deploy fires on slot 1 inside its swap->canary window, AFTER
    # slot 0 already swapped, passed canary and rejoined rotation —
    # the rollback must evict slot 0's live new-revision work through
    # the zero-lost failover before restoring its warm old engine
    reg, r0, r1 = _registry(model, model_b)
    faults = ServingFaultInjector("kill_deploy@1:1")
    rs = _fleet(reg, n=3, faults=faults)
    ctl = DeployController(rs, "m", r1,
                           config=DeployConfig(canary_tolerance=3))
    ctl.start()
    rids, k = [], 0
    while not ctl.done():
        if k < 12:                            # traffic during rollout
            rids.append(rs.add_request(_prompts(1, seed=100 + k)[0],
                                       _sp()))
            k += 1
        rs.step()
        ctl.tick()
    st = ctl.status()
    assert st["outcome"] == "rolled_back", st
    assert "killed in the swap->canary window" in st["error"]
    assert st["swapped"] == [0, 1]
    rs.run(max_steps=3000)

    assert reg.active("m") == r0
    for rep in rs.replicas:
        assert rep.revision == r0
        assert rep.is_serving()
    assert rs.route_weights("m") == {}
    _assert_all_served(rs, rids)              # evicted work re-served
    _assert_no_leaks(rs)
    ids = sorted(obs.reqtrace.traces(prefix=ctl.deploy_id))
    ids += sorted(obs.reqtrace.traces(prefix=f"tr-{rs.label}-"))
    dump = obs.reqtrace.dump_payload("deploy-rollback-test",
                                     trace_ids=ids, complete=True)
    assert obs.reqtrace.check_causality(dump) == []
    dep = [e.kind for e in obs.reqtrace.events(trace_id=ctl.deploy_id)]
    assert dep.count("rollback") == 1 and "deploy_commit" not in dep


# -------------------------------------------------- cross-revision KV
def test_cross_revision_kv_is_refused(model, model_b):
    reg, r0, r1 = _registry(model, model_b)
    rs = _fleet(reg, n=2)
    # park slot 1 and move it to the new revision by hand (mid-deploy
    # shape: a mixed-revision pool)
    rs.drain(1, recompute=False)
    for _ in range(50):
        if rs.replicas[1].state == ReplicaState.DRAINED:
            break
        rs.step()
    assert rs.replicas[1].state == ReplicaState.DRAINED
    assert rs.replicas[1].swap_revision(reg.engine_factory("m", r1))
    assert rs.probe_grow(1)
    assert rs.replicas[0].revision_key() == ("m", r0)
    assert rs.replicas[1].revision_key() == ("m", r1)

    # a live decode on the old-revision slot refuses to migrate across
    # no route weights: steering prefers the registry-active revision,
    # so the request homes on the old-revision slot
    rid = rs.add_request(_prompts(1, seed=21)[0], _sp(mt=8))
    assert rs.get_request(rid).replica == 0
    for _ in range(200):
        if rs.replicas[0].migratable_requests():
            break
        rs.step()
    cand = rs.replicas[0].migratable_requests()
    assert cand, "no decode-phase request to migrate"
    before = rs.migrator.stats()["revision_refused"]
    out = rs.migrator.migrate(rs.replicas[0], rs.replicas[1], cand[0],
                              "rebalance")
    assert out is None                        # clean abort, not a raise
    # …and a peer prefix pull across revisions is refused the same way
    rec = rs.get_request(rid)
    pull = rs.migrator.fetch_prefix(rs.replicas[0], rs.replicas[1],
                                    rid, rec.trace_id,
                                    list(rec.prompt_ids))
    assert pull is None
    assert rs.migrator.stats()["revision_refused"] == before + 2
    rs.run(max_steps=1000)
    assert rs.get_request(rid).finished       # kept running at source
    assert rs.get_request(rid).revision == r0
    _assert_no_leaks(rs)


# --------------------------------------------------------- A/B routing
def test_route_weight_validation_and_steering(model, model_b):
    reg, r0, r1 = _registry(model, model_b)
    rs = _fleet(reg, n=2)
    with pytest.raises(ValueError, match="non-negative"):
        rs.set_route_weights("m", {r0: -1.0})
    with pytest.raises(ValueError, match="positive sum"):
        rs.set_route_weights("m", {r0: 0.0})
    rs.set_route_weights("m", {r0: 1.0, r1: 3.0})
    assert rs.route_weights("m") == {r0: 1.0, r1: 3.0}
    # all weight on a revision no replica serves: availability beats
    # the split — the request admits anyway, pinned to its real home
    rs.set_route_weights("m", {r1: 1.0})
    rid = rs.add_request(_prompts(1, seed=31)[0], _sp(mt=2))
    assert rs.get_request(rid).revision == r0
    rs.set_route_weights("m", None)
    assert rs.route_weights("m") == {}
    rs.run(max_steps=300)
    _assert_no_leaks(rs)


# ------------------------------------------------- invariant 8 (checker)
def _payload(ring, complete=True):
    return {"version": 1, "reason": "test", "complete": complete,
            "events": [e.as_dict() for e in ring.events()]}


def test_invariant8_synthetic_legal_and_violation():
    r = ReqTraceRing()
    # legal: tokens under the admitted revision; the re-dispatch
    # records a fresh `admitted` that re-pins the trace
    r.record("admitted", "t8", router="r0", replica=0, model="m",
             revision="sha256:aaa")
    r.record("engine_admit", "t8", engine="m-r0", arrival=0)
    r.record("scheduled", "t8", arrival=0)
    r.record("prefill", "t8")
    r.record("first_token", "t8", revision="sha256:aaa")
    r.record("requeue", "t8", arrival=0)
    r.record("admitted", "t8", router="r0", replica=1, policy="repin",
             model="m", revision="sha256:bbb")
    r.record("engine_admit", "t8", engine="m-r1", arrival=0)
    r.record("scheduled", "t8", arrival=0)
    r.record("prefill", "t8")
    r.record("decode_chunk", "t8", revision="sha256:bbb")
    r.record("finish", "t8", reason="stop", revision="sha256:bbb")
    assert obs.reqtrace.check_causality(_payload(r)) == []

    # violation: a token from a revision the trace was never re-pinned
    # to — the exact hole a buggy rollout would open
    r.clear()
    r.record("admitted", "t9", router="r0", replica=0, model="m",
             revision="sha256:aaa")
    r.record("engine_admit", "t9", engine="m-r0", arrival=0)
    r.record("scheduled", "t9", arrival=0)
    r.record("prefill", "t9")
    r.record("first_token", "t9", revision="sha256:bbb")
    r.record("finish", "t9", reason="stop", revision="sha256:bbb")
    msgs = obs.reqtrace.check_causality(_payload(r))
    assert any("revision pinning broken" in v for v in msgs), msgs


def test_deploy_trace_terminal_rule():
    r = ReqTraceRing()
    r.record("deploy_start", "dep-t", router="r0", model="m",
             from_revision="sha256:aaa", to_revision="sha256:bbb",
             replicas=2)
    r.record("replica_swap", "dep-t", router="r0", replica=0,
             model="m", revision="sha256:bbb")
    r.record("canary", "dep-t", router="r0", replica=0, mismatches=0,
             passed=True)
    # an in-flight deploy is fine on a partial dump…
    assert obs.reqtrace.check_causality(_payload(r, complete=False)) \
        == []
    # …but a COMPLETE dump demands exactly one terminal
    msgs = obs.reqtrace.check_causality(_payload(r))
    assert any("deploy ended 0 times" in v for v in msgs), msgs
    r.record("deploy_commit", "dep-t", router="r0", model="m",
             revision="sha256:bbb", replicas=1)
    assert obs.reqtrace.check_causality(_payload(r)) == []
    # commit AND rollback on one deploy is a bug wherever it comes from
    r.record("rollback", "dep-t", router="r0", model="m", reason="x",
             restored=0, revision="sha256:aaa")
    msgs = obs.reqtrace.check_causality(_payload(r))
    assert any("deploy ended 2 times" in v for v in msgs), msgs
