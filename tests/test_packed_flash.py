"""Packed-pair flash attention (head_dim 64): parity + gating.

Kernel parity tests need the real TPU (pallas); they skip on the CPU
mesh. The gate/fallback logic tests run everywhere."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

on_tpu = jax.default_backend() == "tpu"


def _pack(a):
    """[B,H,T,D] heads-major -> [B,H/2,T,2D] packed pairs."""
    B, H, T, D = a.shape
    return jnp.reshape(jnp.transpose(
        jnp.reshape(a, (B, H // 2, 2, T, D)), (0, 1, 3, 2, 4)),
        (B, H // 2, T, 2 * D))


def _unpack(a, D):
    B, Hp, T, d2 = a.shape
    return jnp.reshape(jnp.transpose(
        jnp.reshape(a, (B, Hp, T, 2, D)), (0, 1, 3, 2, 4)),
        (B, 2 * Hp, T, D))


@pytest.mark.skipif(not on_tpu, reason="pallas kernel needs the TPU")
@pytest.mark.parametrize("T", [256, 768, 1152, 2048, 6400])
def test_packed_kernel_matches_composed_fwd_bwd(T):
    """T=768 regression: supported() admits any T % 128 == 0 but 512 does
    not divide 768 — the fwd grid must round block_q down to a divisor or
    the tail q-rows are silently never written. T=1152 regression (both
    hazards at once, on the FA2 path): the fwd VMEM bound must floor to
    a power of two (a raw bound like 455 halves to a degenerate block)
    AND the FA2 backward blocks must divide T or the 2D grid leaves the
    dq tail uninitialized and skips the last dk/dv block. T=1152/2048/
    6400 exercise the FA2 backward (fwd-saved lse, 2D grids with causal
    block skipping, f32 accumulator refs; T > BWD_SINGLE_MAX); 6400 also
    walks the FA2 block halving 1024→512→256 (6400 % 1024 = 256)."""
    from paddle_tpu.ops.pallas.packed_flash import packed_flash_attention
    # the composed ORACLE materialises [B, H, T, T] f32 scores: at
    # T=6400 the B2/H4 geometry needs >17G hbm, so large T shrinks it
    B, H, D = (2, 4, 64) if T <= 2048 else (1, 2, 64)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.bfloat16)
    sc = 1.0 / np.sqrt(D)

    def composed(q, k, v):
        s = jnp.einsum("bhtd,bhsd->bhts",
                       q.astype(jnp.float32), k.astype(jnp.float32)) * sc
        row = jnp.arange(T)[:, None]
        col = jnp.arange(T)[None, :]
        s = jnp.where(row >= col, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))

    def packed(q, k, v):
        o = packed_flash_attention(_pack(q), _pack(k), _pack(v), True, sc)
        return _unpack(o, D).astype(jnp.float32)

    ref = jax.jit(composed)(q, k, v)
    got = jax.jit(packed)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)

    def loss_ref(q, k, v):
        return jnp.sum(composed(q, k, v) ** 2)

    def loss_pk(q, k, v):
        return jnp.sum(packed(q, k, v) ** 2)

    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    gp = jax.jit(jax.grad(loss_pk, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        a32 = np.asarray(a, np.float32)
        b32 = np.asarray(b, np.float32)
        scale = np.abs(a32).max() + 1e-6
        assert np.abs(a32 - b32).max() <= 3e-2 * scale, f"d{name} mismatch"


@pytest.mark.skipif(not on_tpu, reason="pallas kernel needs the TPU")
def test_gpt_12head_step_parity_packed_vs_standard():
    """The 12-head GPT train step must produce the same losses with the
    packed path engaged (default) and disabled (min_seq above T)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn

    def run(min_seq):
        paddle.set_flags({"FLAGS_flash_attention_min_seq": min_seq})
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=256, num_layers=2,
                        num_heads=4, max_seq_len=512)
        m = GPT(cfg)
        optim = opt.AdamW(1e-3, parameters=m.parameters())
        step = paddle.jit.TrainStep(m, lambda mm, x, y: gpt_loss_fn(
            mm, x, y), optim)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randint(0, 256, (2, 512)).astype("int32"))
        y = paddle.to_tensor(rng.randint(0, 256, (2, 512)).astype("int32"))
        return [float(step(x, y).numpy()) for _ in range(3)]

    from paddle_tpu.core import flags as _flags
    prev = _flags.flag("flash_attention_min_seq")
    try:
        packed = run(512)    # T=512, d=64 -> packed path
        standard = run(4096)  # threshold above T -> composed path
    finally:
        paddle.set_flags({"FLAGS_flash_attention_min_seq": prev})
    np.testing.assert_allclose(packed, standard, rtol=5e-3, atol=5e-3)


@pytest.mark.skipif(not on_tpu, reason="pallas kernel needs the TPU")
def test_bert_step_parity_packed_vs_standard():
    """BERT (non-causal) packed-pair routing: same losses with the packed
    path engaged (T >= min_seq, d=64, even heads, no mask) and disabled."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        bert_pretrain_loss_fn,
                                        make_bert_pretrain_batch)

    def run(min_seq):
        paddle.set_flags({"FLAGS_flash_attention_min_seq": min_seq})
        paddle.seed(0)
        cfg = BertConfig(vocab_size=256, hidden_size=256, num_layers=2,
                         num_heads=4, max_position=512)
        m = BertForPretraining(cfg)
        optim = opt.AdamW(1e-3, parameters=m.parameters())
        step = paddle.jit.TrainStep(m, bert_pretrain_loss_fn, optim)
        rng = np.random.RandomState(0)
        batch = make_bert_pretrain_batch(rng, cfg.vocab_size, 2, 512)
        args = [paddle.to_tensor(a) for a in batch]
        return [float(step(*args).numpy()) for _ in range(3)]

    from paddle_tpu.core import flags as _flags
    from paddle_tpu.nn.functional import attention as A
    prev = _flags.flag("flash_attention_min_seq")
    try:
        packed = run(512)     # T=512, d=64 -> packed (non-causal) path
        # guard against a vacuous pass: if the packed kernel regressed,
        # SDPA silently unpacks to the composed path and both runs would
        # agree without the kernel ever executing
        assert A.LAST_PATH == "flash", (
            f"packed path did not engage (LAST_PATH={A.LAST_PATH})")
        standard = run(4096)  # threshold above T -> composed path
        assert A.LAST_PATH == "composed"
    finally:
        paddle.set_flags({"FLAGS_flash_attention_min_seq": prev})
    np.testing.assert_allclose(packed, standard, rtol=5e-3, atol=5e-3)


def test_pack_gate_scope():
    from paddle_tpu.ops.pallas import packed_flash
    if not on_tpu:
        assert not packed_flash.supported(64, 12, 1024, 1024)
        return
    assert packed_flash.supported(64, 12, 1024, 1024)
    assert packed_flash.supported(64, 12, 2048, 2048)   # FA2 bwd
    assert packed_flash.supported(64, 12, 8192, 8192)   # FA2 bwd blk1024
    assert not packed_flash.supported(128, 6, 1024, 1024)   # d=128: no need
    assert not packed_flash.supported(64, 11, 1024, 1024)   # odd heads
    assert not packed_flash.supported(64, 12, 16384, 16384)  # MAX_SEQ gate
    assert not packed_flash.supported(64, 12, 1024, 512)    # cross-attn
