"""Auto-checkpoint tests (reference: test_auto_checkpoint.py — epoch-ranged
training resumes after a kill with identical state)."""
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.checkpoint import (AutoCheckpointManager,
                                            load_sharded_state,
                                            save_sharded_state)


def _build(seed=7):
    # fresh unique_name scope = the fresh-process contract: a resumed job
    # rebuilds the model with identical auto-generated parameter names
    with paddle.utils.unique_name.guard():
        paddle.seed(seed)
        model = paddle.nn.Linear(4, 2)
        optim = opt.Adam(1e-2, parameters=model.parameters())
        sched = opt.lr.StepDecay(learning_rate=0.01, step_size=2)
    return model, optim, sched


def _epoch(model, optim, X, Y):
    loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
    loss.backward()
    optim.step()
    optim.clear_grad()
    return float(loss.numpy())


def test_kill_and_resume_reproduces_losses(tmp_path):
    X = np.random.RandomState(0).randn(8, 4).astype("float32")
    Y = np.random.RandomState(1).randn(8, 2).astype("float32")

    # uninterrupted run: 6 epochs
    model, optim, sched = _build()
    ref_losses = [_epoch(model, optim, X, Y) for _ in range(6)]

    # interrupted run: 3 epochs, "crash", new process resumes
    d = str(tmp_path / "acp")
    model1, optim1, sched1 = _build()
    acp1 = AutoCheckpointManager(d, models=[model1], optimizers=[optim1],
                                 lr_schedulers=[sched1])
    run1 = []
    for epoch in acp1.train_epoch_range(6):
        run1.append(_epoch(model1, optim1, X, Y))
        if epoch == 2:
            # simulated preemption: epoch 2's work finishes but its
            # checkpoint never lands (a real kill loses it too) — the last
            # durable snapshot is epoch 1's
            break

    model2, optim2, sched2 = _build(seed=999)  # different init: must restore
    acp2 = AutoCheckpointManager(d, models=[model2], optimizers=[optim2],
                                 lr_schedulers=[sched2])
    run2 = []
    first = None
    for epoch in acp2.train_epoch_range(6):
        if first is None:
            first = epoch
        run2.append(_epoch(model2, optim2, X, Y))
    assert first == 2  # resumes by re-running the lost epoch
    np.testing.assert_allclose(run1[:2] + run2, ref_losses, rtol=1e-5)


def test_async_save_resumes_identically(tmp_path):
    """async_save=True must produce the same resumable snapshots as sync
    (background writes joined at range end / before restore), and the
    snapshot must be immune to post-save parameter mutation (state is
    host-materialised before the thread starts)."""
    X = np.random.RandomState(0).randn(8, 4).astype("float32")
    Y = np.random.RandomState(1).randn(8, 2).astype("float32")

    model, optim, sched = _build()
    ref_losses = [_epoch(model, optim, X, Y) for _ in range(5)]

    d = str(tmp_path / "acp_async")
    m1, o1, s1 = _build()
    acp1 = AutoCheckpointManager(d, models=[m1], optimizers=[o1],
                                 lr_schedulers=[s1], async_save=True)
    run1 = []
    for epoch in acp1.train_epoch_range(5):
        run1.append(_epoch(m1, o1, X, Y))
        if epoch == 2:
            # saves fire on generator resume, so breaking here loses
            # epoch 2's snapshot exactly like the sync kill test; the last
            # durable one is epoch 1's ASYNC write, joined by the
            # generator's finally on close (break → GeneratorExit)
            break

    m2, o2, s2 = _build(seed=999)
    acp2 = AutoCheckpointManager(d, models=[m2], optimizers=[o2],
                                 lr_schedulers=[s2], async_save=True)
    first = None
    run2 = []
    for epoch in acp2.train_epoch_range(5):
        first = epoch if first is None else first
        run2.append(_epoch(m2, o2, X, Y))
    assert first == 2  # resumed from epoch-1's async snapshot
    np.testing.assert_allclose(run1[:2] + run2, ref_losses, rtol=1e-5)


def test_async_save_error_surfaces(tmp_path):
    """A failed background write must raise at the next wait()/save, not
    vanish."""
    import pytest
    m, o, s = _build()
    acp = AutoCheckpointManager(str(tmp_path / "x"), models=[m],
                                optimizers=[o], lr_schedulers=[s])
    acp.save_async(0)
    acp.wait()

    def boom(state, epoch):
        raise IOError("disk full")
    acp._write = boom
    acp.save_async(1)
    with pytest.raises(IOError, match="disk full"):
        acp.wait()
    # error is consumed; manager is usable again
    acp.wait()


def test_checkpoint_prune_keeps_max(tmp_path):
    d = str(tmp_path / "acp")
    model, optim, sched = _build()
    acp = AutoCheckpointManager(d, models=[model], optimizers=[optim],
                                max_keep=2)
    for e in range(5):
        acp.save(e)
    kept = sorted(int(n.split("_")[1]) for n in os.listdir(d)
                  if n.startswith("epoch_"))
    assert kept == [3, 4]


def test_module_level_register_api(tmp_path):
    from paddle_tpu.incubate import checkpoint as acp_mod
    model, optim, _ = _build()
    acp_mod.register(str(tmp_path / "acp2"), models=[model],
                     optimizers=[optim])
    X = np.random.randn(4, 4).astype("float32")
    Y = np.random.randn(4, 2).astype("float32")
    seen = list(acp_mod.train_epoch_range(2))
    assert seen == [0, 1]


def test_sharded_save_roundtrip(tmp_path):
    """Sharded arrays on the 8-device mesh save per-shard and reassemble."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    y = np.random.randn(3, 5).astype("float32")
    ys = jax.device_put(y, NamedSharding(mesh, P()))
    d = str(tmp_path / "sharded")
    save_sharded_state({"x": xs, "y": ys}, d)
    back = load_sharded_state(d)
    np.testing.assert_array_equal(back["x"], x)
    np.testing.assert_array_equal(back["y"], y)


def test_corrupt_snapshot_falls_back_to_previous(tmp_path):
    """Round-3 verdict weak #8: durability against remote-fs failure
    modes. A snapshot corrupted AFTER its atomic rename (disk truncation)
    must not brick the resume path — restore_latest quarantines it and
    falls back to the previous epoch; stale crashed-save temp dirs are
    swept on the next save."""
    import warnings

    model, optim, sched = _build()
    mgr = AutoCheckpointManager(str(tmp_path), [model], [optim], [sched],
                                save_interval_epochs=1, max_keep=3)
    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    for e in range(3):
        _epoch(model, optim, X, Y)
        mgr.save(e)
    w_epoch1 = model.weight.numpy().copy()  # state as of the last save

    # simulate a crashed writer: partial temp dir (never renamed)
    stale = tmp_path / ".tmp_crashed"
    stale.mkdir()
    (stale / "state.pdparams").write_bytes(b"partial")

    # corrupt the NEWEST snapshot post-rename (truncation)
    newest = tmp_path / "epoch_2" / "state.pdparams"
    newest.write_bytes(newest.read_bytes()[:10])

    model2, optim2, sched2 = _build()
    mgr2 = AutoCheckpointManager(str(tmp_path), [model2], [optim2],
                                 [sched2], save_interval_epochs=1, max_keep=3)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        got = mgr2.restore_latest()
    assert got == 1  # fell back past the corrupt epoch_2
    assert any("corrupt" in str(w.message) for w in rec)
    assert (tmp_path / "epoch_2.corrupt").exists()  # quarantined
    # the fallback snapshot's state actually loaded
    _epoch(model2, optim2, X, Y)

    # next save sweeps the stale temp dir
    mgr2.save(5)
    assert not stale.exists()
    assert (tmp_path / "epoch_5" / "meta.json").exists()


def test_bit_flip_detected_by_checksum_manifest(tmp_path):
    """Silent bit rot: a single flipped bit inside a weight array leaves
    the pickle perfectly parseable — only the per-array sha256 manifest
    (checksums.json) can catch it. restore_latest must quarantine the
    rotten snapshot and fall back to the previous one."""
    import warnings

    model, optim, sched = _build()
    mgr = AutoCheckpointManager(str(tmp_path), [model], [optim], [sched],
                                save_interval_epochs=1, max_keep=3)
    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    for e in range(2):
        _epoch(model, optim, X, Y)
        mgr.save(e)
    assert (tmp_path / "epoch_1" / "checksums.json").exists()
    w_epoch0 = model.weight.numpy().copy()

    # flip ONE bit of the weight array inside the newest snapshot
    target = tmp_path / "epoch_1" / "state.pdparams"
    blob = bytearray(target.read_bytes())
    needle = model.weight.numpy().tobytes()
    at = bytes(blob).find(needle)
    assert at >= 0, "weight bytes not found in serialized snapshot"
    blob[at + 3] ^= 0x01
    target.write_bytes(bytes(blob))

    model2, optim2, sched2 = _build()
    mgr2 = AutoCheckpointManager(str(tmp_path), [model2], [optim2],
                                 [sched2], save_interval_epochs=1,
                                 max_keep=3)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        got = mgr2.restore_latest()
    assert got == 0                     # fell back past the rotten epoch_1
    assert any("checksum mismatch" in str(w.message) for w in rec)
    assert (tmp_path / "epoch_1.corrupt").exists()
    # NOTE: epoch_0's weight predates the last _epoch; just confirm the
    # fallback restored cleanly and training can continue
    assert model2.weight.numpy().shape == w_epoch0.shape
    _epoch(model2, optim2, X, Y)


def test_require_manifest_refuses_manifestless_snapshot(tmp_path):
    """Strict-manifest mode (require_manifest=True, the mode published
    model revisions restore with — serving/deploy.py): a snapshot whose
    checksums.json was DELETED is unverifiable and must be refused like
    any corrupt snapshot — quarantined with a warning, restore falls
    back to the newest snapshot that still carries its manifest."""
    import warnings

    model, optim, sched = _build()
    mgr = AutoCheckpointManager(str(tmp_path), [model], [optim], [sched],
                                save_interval_epochs=1, max_keep=3)
    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    for e in range(2):
        _epoch(model, optim, X, Y)
        mgr.save(e)
    os.remove(tmp_path / "epoch_1" / "checksums.json")

    model2, optim2, sched2 = _build(seed=999)
    mgr2 = AutoCheckpointManager(str(tmp_path), [model2], [optim2],
                                 [sched2], require_manifest=True)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        got = mgr2.restore_latest()
    assert got == 0                    # fell back past manifestless epoch_1
    assert any("no checksums.json" in str(w.message) for w in rec)
    assert (tmp_path / "epoch_1.corrupt").exists()
    _epoch(model2, optim2, X, Y)       # fallback state actually loaded


def test_missing_manifest_stays_restorable(tmp_path):
    """Pre-manifest snapshots (no checksums.json) must restore without
    complaint — the integrity layer is additive, not a format break."""
    model, optim, sched = _build()
    mgr = AutoCheckpointManager(str(tmp_path), [model], [optim], [sched])
    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    _epoch(model, optim, X, Y)
    mgr.save(0)
    os.remove(tmp_path / "epoch_0" / "checksums.json")
    model2, optim2, sched2 = _build()
    mgr2 = AutoCheckpointManager(str(tmp_path), [model2], [optim2],
                                 [sched2])
    assert mgr2.restore_latest() == 0
    np.testing.assert_array_equal(model2.weight.numpy(),
                                  model.weight.numpy())
