"""Elastic fault-tolerance e2e: supervisor × step-checkpoints × injectors.

The contract under test (ISSUE 1 tentpole): a worker killed at step N under
the ElasticSupervisor restarts with backoff, resumes from the last
step-granular auto-checkpoint, and reaches BITWISE-identical final
parameters to an uninterrupted run — because every batch in ft_worker.py is
a pure function of the step index and restore round-trips f32 exactly.
Corrupt-checkpoint and hang (heartbeat) faults ride the same path; NaN
injection exercises the anomaly guard's skip_step policy in-process.
"""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.elastic import (ElasticJobError,
                                            ElasticSupervisor, WorkerSpec)
from paddle_tpu.testing.faults import KILL_EXIT_CODE, FaultInjector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "ft_worker.py")

TOTAL_STEPS = 12
SAVE_EVERY = 4


def _run_supervised(tmp, tag, faults=None, policy=None, max_restarts=3,
                    heartbeat_timeout=None, total=TOTAL_STEPS):
    """Run one supervised ft_worker job; returns (result dict, report)."""
    ckpt = os.path.join(str(tmp), f"{tag}_ckpt")
    out = os.path.join(str(tmp), f"{tag}_out.json")
    state = os.path.join(str(tmp), f"{tag}_faults")
    env = {
        "FT_CKPT_DIR": ckpt,
        "FT_OUT": out,
        "FT_TOTAL_STEPS": str(total),
        "FT_SAVE_EVERY": str(SAVE_EVERY),
        "JAX_PLATFORMS": "cpu",
        # single host device: workers don't need the test mesh
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    if faults:
        env["PADDLE_TPU_FAULTS"] = faults
        env["PADDLE_TPU_FAULT_STATE_DIR"] = state
    if policy:
        env["FT_ANOMALY_POLICY"] = policy
    os.makedirs(ckpt, exist_ok=True)
    sup = ElasticSupervisor(max_restarts=max_restarts, backoff_base=0.05,
                            backoff_max=0.2, jitter=0.1,
                            heartbeat_timeout=heartbeat_timeout,
                            monitor_interval=0.02,
                            heartbeat_dir=os.path.join(str(tmp),
                                                       f"{tag}_hb"),
                            seed=0)
    log = os.path.join(str(tmp), f"{tag}.log")
    report = sup.run([WorkerSpec([sys.executable, WORKER], env=env,
                                 log_path=log)])
    assert os.path.exists(out), _tail(log)
    with open(out) as f:
        return json.load(f), report


def _tail(log, n=2000):
    try:
        with open(log) as f:
            return f.read()[-n:]
    except OSError:
        return "<no worker log>"


def _params(result):
    return {k: np.asarray(v, dtype=np.float32)
            for k, v in result["params"].items()}


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """One uninterrupted supervised run; every fault scenario must land on
    exactly these parameters."""
    tmp = tmp_path_factory.mktemp("ft_baseline")
    result, report = _run_supervised(tmp, "base")
    assert report["restarts"] == {0: 0}
    assert result["steps_run"] == TOTAL_STEPS
    return _params(result)


def test_kill_restart_resumes_bitwise_identical(tmp_path, baseline):
    """Acceptance: kill at step 6 (mid-interval, checkpoint at step 3) →
    supervisor restarts → worker resumes from step_3 and replays — final
    params bitwise equal to the uninterrupted run."""
    result, report = _run_supervised(tmp_path, "kill", faults="kill@6")
    assert report["restarts"] == {0: 1}
    assert report["history"][0][0][1] == f"exit code {KILL_EXIT_CODE}"
    # resumed from the step_3 snapshot, not epoch 0: first step of the
    # second incarnation is 4
    assert result["restart_count"] == 1
    assert result["first_step"] == SAVE_EVERY
    got = _params(result)
    assert sorted(got) == sorted(baseline)
    for k in baseline:
        np.testing.assert_array_equal(got[k], baseline[k], err_msg=k)


def test_corrupt_checkpoint_quarantine_end_to_end(tmp_path, baseline):
    """Acceptance: the crash also tears the newest snapshot (corrupt@9 then
    kill@9 — at step 9 the newest landed snapshot is step_7's).
    restore_latest must quarantine the torn step_7, fall back to the
    next-newest (step_3), and the job still completes and converges
    bitwise."""
    result, report = _run_supervised(tmp_path, "corrupt",
                                     faults="corrupt@9,kill@9")
    assert report["restarts"] == {0: 1}
    # the torn snapshot was quarantined, not retried forever
    assert result["quarantined"], "no .corrupt quarantine dir produced"
    # fell back to an OLDER snapshot: step_7 was torn, so resume re-ran
    # from step 4 (the step_3 snapshot)
    assert result["first_step"] == SAVE_EVERY
    got = _params(result)
    for k in baseline:
        np.testing.assert_array_equal(got[k], baseline[k], err_msg=k)


def test_nan_skip_step_counted_not_fatal(tmp_path, baseline):
    """Acceptance: NaN loss at step 5 under policy='skip_step' is dropped
    and counted; training completes without restarts and the final params
    differ from baseline ONLY by the missing step's update (sanity: all
    finite, job ran all steps)."""
    result, report = _run_supervised(tmp_path, "nan", faults="nan@5",
                                     policy="skip_step")
    assert report["restarts"] == {0: 0}
    assert result["steps_run"] == TOTAL_STEPS
    assert result["anomaly"]["skipped_steps"] == 1
    assert result["anomaly"]["checked_steps"] == TOTAL_STEPS
    got = _params(result)
    for k in baseline:
        assert np.isfinite(got[k]).all(), f"{k} poisoned despite skip_step"


def test_nan_raise_policy_exhausts_restart_budget(tmp_path):
    """Under policy='raise' a deterministic NaN is NOT transient: every
    incarnation re-fires it (fresh fault state per attempt would be a
    different scenario — here the marker fires once, but the raise happens
    before any checkpoint at step 1, so the retry replays it... therefore
    the supervisor must eventually surface ElasticJobError)."""
    ckpt = os.path.join(str(tmp_path), "raise_ckpt")
    out = os.path.join(str(tmp_path), "raise_out.json")
    env = {
        "FT_CKPT_DIR": ckpt, "FT_OUT": out,
        "FT_TOTAL_STEPS": "6", "FT_SAVE_EVERY": "100",
        "FT_ANOMALY_POLICY": "raise",
        "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
        "PADDLE_TPU_FAULTS": "nan@1",
        # no PADDLE_TPU_FAULT_STATE_DIR: untracked mode re-fires every
        # incarnation, modelling a PERSISTENT data-poisoning fault
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    os.makedirs(ckpt, exist_ok=True)
    sup = ElasticSupervisor(max_restarts=1, backoff_base=0.05,
                            backoff_max=0.1, monitor_interval=0.02,
                            heartbeat_dir=os.path.join(str(tmp_path), "hb"),
                            seed=0)
    with pytest.raises(ElasticJobError) as ei:
        sup.run([WorkerSpec([sys.executable, WORKER], env=env)])
    assert len(ei.value.history) == 2  # initial attempt + 1 restart


def test_hang_detected_by_heartbeat_and_restarted(tmp_path, baseline):
    """A worker stalled at step 8 stops beating; the supervisor kills and
    restarts it, and the resumed run still converges bitwise."""
    result, report = _run_supervised(tmp_path, "stall",
                                     faults="stall@8:3600",
                                     heartbeat_timeout=1.5)
    assert report["restarts"] == {0: 1}
    assert "hang" in report["history"][0][0][1]
    got = _params(result)
    for k in baseline:
        np.testing.assert_array_equal(got[k], baseline[k], err_msg=k)


# ---------------------------------------------------------------- unit level
def test_backoff_is_capped_exponential_with_jitter():
    sup = ElasticSupervisor(backoff_base=0.5, backoff_factor=2.0,
                            backoff_max=3.0, jitter=0.25, seed=42)
    d0, d1, d2, d5 = (sup.backoff_delay(n) for n in (0, 1, 2, 5))
    assert 0.5 <= d0 <= 0.5 * 1.25
    assert 1.0 <= d1 <= 1.0 * 1.25
    assert 2.0 <= d2 <= 2.0 * 1.25
    assert 3.0 <= d5 <= 3.0 * 1.25  # capped at backoff_max before jitter
    # jitter decorrelates identically-configured supervisors
    other = ElasticSupervisor(backoff_base=0.5, backoff_factor=2.0,
                              backoff_max=3.0, jitter=0.25, seed=7)
    assert any(abs(sup.backoff_delay(n) - other.backoff_delay(n)) > 1e-9
               for n in range(4))


def test_fault_injector_fire_once_markers(tmp_path):
    inj = FaultInjector("nan@3", state_dir=str(tmp_path))
    assert inj.enabled
    x = 2.0
    assert np.isnan(inj.poison_loss(3, x))
    assert inj.fired("nan", 3)
    assert inj.poison_loss(3, x) == x  # second incarnation: already fired
    assert inj.poison_loss(2, x) == x  # other steps untouched
    inert = FaultInjector("", state_dir=None)
    assert not inert.enabled
    assert inert.poison_loss(3, x) == x


def test_fault_injector_rejects_bad_spec():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultInjector("explode@3")


def test_launch_elastic_env_carries_global_rank(monkeypatch):
    """REVIEW high: the elastic launch branch must put the globally
    numbered PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM into spec.env —
    otherwise the supervisor defaults them to the local spec index and
    gang size, and a multi-node launch silently degenerates into
    independent per-node jobs."""
    import importlib
    # the package re-exports the launch() function under the same name, so
    # attribute access yields the function — import the module explicitly
    launch_mod = importlib.import_module("paddle_tpu.distributed.launch")

    captured = {}

    def fake_run(self, specs):
        captured["specs"] = specs
        return {}

    monkeypatch.setattr(ElasticSupervisor, "run", fake_run)
    monkeypatch.setattr(sys, "argv", [
        "launch", "--ips", "10.0.0.1,10.0.0.2", "--nproc_per_node", "2",
        "--node_rank", "1", "--max_restarts", "2", "train.py"])
    launch_mod.launch()

    specs = captured["specs"]
    assert [s.env["PADDLE_TRAINER_ID"] for s in specs] == ["2", "3"]
    assert [s.env["PADDLE_TRAINERS_NUM"] for s in specs] == ["4", "4"]
    # rank/endpoint consistency: the endpoint indexed by the global rank
    assert [s.env["PADDLE_CURRENT_ENDPOINT"] for s in specs] == \
        ["10.0.0.2:6170", "10.0.0.2:6171"]


def test_epoch_range_ignores_newer_step_snapshot(tmp_path):
    """REVIEW medium: restore_latest returns the newest snapshot of EITHER
    kind; train_epoch_range must not read a step snapshot's index as an
    epoch (which would silently skip up to that many epochs)."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.checkpoint import AutoCheckpointManager

    with paddle.utils.unique_name.guard():
        paddle.seed(3)
        m = paddle.nn.Linear(4, 2)
        o = opt.SGD(0.1, parameters=m.parameters())
    d = str(tmp_path / "mixed")
    acp = AutoCheckpointManager(d, models=[m], optimizers=[o],
                                save_every_n_steps=2)
    for _ in acp.train_step_range(6):
        pass  # leaves step snapshots, newest step_5

    acp2 = AutoCheckpointManager(d, models=[m], optimizers=[o])
    epochs = list(acp2.train_epoch_range(3))
    assert acp2.restored_kind == "step"  # step_5 WAS the newest snapshot
    assert epochs == [0, 1, 2]  # ...but must not fast-forward the epochs


def test_step_range_resumes_in_process(tmp_path):
    """train_step_range unit check (no subprocess): a run broken at step 6
    resumes at the step after its last step-granular snapshot."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.checkpoint import AutoCheckpointManager

    def build():
        with paddle.utils.unique_name.guard():
            paddle.seed(3)
            m = paddle.nn.Linear(4, 2)
            o = opt.SGD(0.1, parameters=m.parameters())
        return m, o

    def one(m, o, step):
        rs = np.random.RandomState(step)
        X = rs.randn(4, 4).astype("float32")
        loss = (m(paddle.to_tensor(X)) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()

    d = str(tmp_path / "steps")
    m1, o1 = build()
    acp1 = AutoCheckpointManager(d, models=[m1], optimizers=[o1],
                                 save_every_n_steps=3)
    for step in acp1.train_step_range(10):
        one(m1, o1, step)
        if step == 6:
            break  # "crash": step-5 snapshot is the last durable one

    m2, o2 = build()
    acp2 = AutoCheckpointManager(d, models=[m2], optimizers=[o2],
                                 save_every_n_steps=3)
    seen = []
    for step in acp2.train_step_range(10):
        seen.append(step)
        one(m2, o2, step)
    assert seen[0] == 6  # resumed from step_5, replaying the lost step 6
    assert acp2.restored_kind == "step" and acp2.restored_index == 5

    # uninterrupted reference
    m3, o3 = build()
    for step in range(10):
        one(m3, o3, step)
    np.testing.assert_array_equal(m2.weight.numpy(), m3.weight.numpy())
