"""Op benchmark harness (testing/op_bench.py): run, logs-dir layout,
and the develop-vs-PR regression gate (reference op_tester.cc +
tools/check_op_benchmark_result.py)."""
import json
import os

import pytest

from paddle_tpu.testing import op_bench


def test_run_small_corpus(tmp_path):
    cases = [c for c in op_bench.default_cases(large=False)
             if c.name in ("matmul", "softmax", "top_k", "reduce_sum")]
    assert len(cases) == 4
    for c in cases:
        c.repeat = 2
    recs = op_bench.run_cases(cases, str(tmp_path), verbose=False)
    by_name = {r["name"]: r for r in recs}
    # every case produced a timing, none errored
    for name in ("matmul", "softmax", "reduce_sum", "top_k"):
        assert "error" not in by_name[name], by_name[name]
        assert by_name[name]["fwd_ms"] > 0
    # differentiable cases also time fwd+bwd; top_k (int indices) doesn't
    assert "fwd_bwd_ms" in by_name["matmul"]
    assert "fwd_bwd_ms" not in by_name["top_k"]
    # one log file per case, last line parseable (the logs-dir layout the
    # reference gate consumes)
    for name in by_name:
        path = tmp_path / f"{name}.log"
        assert path.exists()
        rec = json.loads(path.read_text().strip().splitlines()[-1])
        assert rec["name"] == name


def test_compare_gate(tmp_path):
    dev, pr = tmp_path / "dev", tmp_path / "pr"
    os.makedirs(dev), os.makedirs(pr)

    def write(d, name, fwd, bwd=None):
        rec = {"name": name, "fwd_ms": fwd}
        if bwd is not None:
            rec["fwd_bwd_ms"] = bwd
        (d / f"{name}.log").write_text(json.dumps(rec) + "\n")

    write(dev, "matmul", 1.0, 3.0)
    write(pr, "matmul", 1.2, 3.0)          # fwd +20%: regression
    write(dev, "softmax", 2.0)
    write(pr, "softmax", 1.8)              # improvement

    rows = op_bench.compare_dirs(str(dev), str(pr), threshold=0.05)
    by = {(r["name"], r["metric"]): r for r in rows}
    assert by[("matmul", "fwd_ms")]["regressed"]
    assert not by[("matmul", "fwd_bwd_ms")]["regressed"]
    assert not by[("softmax", "fwd_ms")]["regressed"]
    # CLI gate exit code: 1 when any regression
    assert op_bench.main(["--compare", str(dev), str(pr)]) == 1
    assert op_bench.main(["--compare", str(dev), str(pr),
                          "--threshold", "0.5"]) == 0

    # a case that ran on develop but is MISSING from (or ERRORED in) the
    # PR logs is a regression — a PR that breaks an op entirely must not
    # sail through the speed gate
    write(dev, "only_dev", 1.0)
    rows = op_bench.compare_dirs(str(dev), str(pr), threshold=0.5)
    by = {(r["name"], r["metric"]): r for r in rows}
    assert by[("only_dev", "status")]["regressed"]
    (pr / "only_dev.log").write_text(json.dumps(
        {"name": "only_dev", "error": "TypeError: boom"}) + "\n")
    rows = op_bench.compare_dirs(str(dev), str(pr), threshold=0.5)
    by = {(r["name"], r["metric"]): r for r in rows}
    assert by[("only_dev", "status")]["regressed"]
    assert "boom" in by[("only_dev", "status")]["detail"]
    assert op_bench.main(["--compare", str(dev), str(pr),
                          "--threshold", "0.5"]) == 1
    # already-broken-on-develop cases have no baseline: not compared
    write(dev, "pre_broken", 1.0)
    (dev / "pre_broken.log").write_text(json.dumps(
        {"name": "pre_broken", "error": "old"}) + "\n")
    rows = op_bench.compare_dirs(str(dev), str(pr), threshold=0.5)
    assert ("pre_broken", "status") not in {(r["name"], r["metric"])
                                            for r in rows}


def test_cli_runs_subset(tmp_path, capsys):
    rc = op_bench.main(["--ops", "matmul", "--small", "--repeat", "2",
                        "--out", str(tmp_path / "logs")])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["name"] == "matmul" and rec["fwd_ms"] > 0
    assert (tmp_path / "logs" / "matmul.log").exists()
    # unknown op name -> exit 2
    assert op_bench.main(["--ops", "nope", "--small"]) == 2
