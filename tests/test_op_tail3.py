"""Round-3 op-tail tests: the coverage-gate closure batch.

Oracles: numpy/torch manual formulas (the reference verifies these families
through OpTest CPU kernels); FD grad checks for the differentiable ops via
the declarative harness.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.testing import OpTestCase, run_case
from paddle_tpu.ops import (creation, detection_ops, extra_ops, fused_ops,
                            metrics_ops, optimizer_ops, quant_ops,
                            rnn_unit_ops, sequence_ops, vision_ops)
from paddle_tpu.ops import array_ops

rng = np.random.RandomState(11)


def r(*shape, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


def t(x, **kw):
    return paddle.to_tensor(x, **kw)


C = OpTestCase

CASES = [
    C(extra_ops.add_position_encoding, (r(2, 4, 6),), dict(alpha=1.0,
      beta=1.0), grad=(0,), op_types=["add_position_encoding"]),
    C(extra_ops.affine_channel, (r(2, 3, 2, 2), r(3), r(3)), ref=lambda x,
      s, b: x * s[None, :, None, None] + b[None, :, None, None],
      grad=(0, 1, 2), op_types=["affine_channel"]),
    C(extra_ops.bilinear_tensor_product, (r(2, 3), r(2, 4), r(5, 3, 4)),
      ref=lambda x, y, w: np.einsum("bm,omn,bn->bo", x, w, y),
      grad=(0, 1, 2), atol=1e-2, rtol=1e-2,
      op_types=["bilinear_tensor_product"]),
    C(extra_ops.modified_huber_loss,
      (np.array([2.0, 0.5, -2.0], np.float32),
       np.array([1.0, 1.0, 1.0], np.float32)),
      ref=lambda x, y: np.array([0.0, 0.25, 8.0]),
      op_types=["modified_huber_loss"]),
    C(extra_ops.batch_fc, (r(2, 3, 4), r(2, 4, 5), r(2, 5)),
      ref=lambda x, w, b: np.einsum("sbi,sio->sbo", x, w) + b[:, None],
      grad=(0, 1, 2), atol=1e-2, rtol=1e-2, op_types=["batch_fc"]),
    C(extra_ops.squared_l2_distance, (r(3, 4), r(3, 4)),
      ref=lambda x, y: ((x - y) ** 2).sum(1)[:, None], grad=(0, 1),
      op_types=["squared_l2_distance"]),
    C(fused_ops.fusion_squared_mat_sub, (r(2, 3), r(3, 4)),
      ref=lambda x, y: (x @ y) ** 2 - (x ** 2) @ (y ** 2),
      atol=5e-2, rtol=5e-2, grad=(0, 1), grad_atol=5e-2,
      op_types=["fusion_squared_mat_sub"]),
    C(fused_ops.skip_layernorm, (r(2, 3, 8), r(2, 3, 8)),
      grad=(0, 1), op_types=["skip_layernorm"]),
    C(creation.diag_embed, (r(2, 3),),
      ref=lambda x: torch.diag_embed(torch.tensor(x)).numpy(),
      grad=(0,), op_types=["diag_embed"]),
    C(detection_ops.polygon_box_transform, (r(1, 2, 2, 3),),
      op_types=["polygon_box_transform"]),
    C(detection_ops.box_clip,
      (np.array([[-5., -5., 300., 200.]], np.float32),
       np.array([100., 150., 1.], np.float32)),
      ref=lambda b, i: np.array([[0., 0., 149., 99.]]),
      op_types=["box_clip"]),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_tail3_case(case):
    run_case(case)


def test_sequence_tail_round3():
    x1 = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    x2 = 100 + np.arange(8, dtype=np.float32).reshape(2, 2, 2)
    out, lens = sequence_ops.sequence_concat(
        [t(x1), t(x2)], [t(np.array([2, 3])), t(np.array([1, 2]))])
    np.testing.assert_array_equal(lens.numpy(), [3, 5])
    np.testing.assert_allclose(out.numpy()[0][:3],
                               np.concatenate([x1[0, :2], x2[0, :1]]))
    np.testing.assert_allclose(out.numpy()[1][:5],
                               np.concatenate([x1[1, :3], x2[1, :2]]))

    # sequence_conv vs manual context-window matmul
    x = r(1, 4, 2)
    w = r(6, 3)
    out = sequence_ops.sequence_conv(t(x), t(np.array([4])), t(w),
                                     context_start=-1, context_length=3)
    ctx = np.zeros((4, 6), np.float32)
    for i in range(4):
        for k in range(3):
            j = i - 1 + k
            if 0 <= j < 4:
                ctx[i, k * 2:(k + 1) * 2] = x[0, j]
    np.testing.assert_allclose(out.numpy()[0], ctx @ w, rtol=1e-4,
                               atol=1e-4)

    e = sequence_ops.sequence_enumerate(t(np.array([[1, 2, 3, 0]])),
                                        t(np.array([3])), 2, pad_value=0)
    np.testing.assert_array_equal(e.numpy()[0],
                                  [[1, 2], [2, 3], [3, 0], [0, 0]])

    sc = sequence_ops.sequence_scatter(
        t(np.zeros((2, 5), np.float32)), t(np.array([[1, 3], [0, 0]])),
        t(np.array([[1., 2.], [5., 9.]], np.float32)), t(np.array([2, 1])))
    np.testing.assert_allclose(sc.numpy(), [[0, 1, 0, 2, 0],
                                            [5, 0, 0, 0, 0]])

    ea = sequence_ops.sequence_expand_as(
        t(np.array([[7.], [8.]], np.float32)), t(np.array([2, 3])))
    np.testing.assert_allclose(ea.numpy()[:, :, 0],
                               [[7, 7, 0], [8, 8, 8]])

    tk = sequence_ops.sequence_topk_avg_pooling(
        t(np.array([[[5., 1., 3., 0.]]], np.float32)), t(np.array([3])),
        [1, 2])
    np.testing.assert_allclose(tk.numpy()[0], [5.0, 4.0])

    al, ln = sequence_ops.ctc_align(t(np.array([[1, 1, 0, 2, 2]])),
                                    t(np.array([5])), blank=0)
    np.testing.assert_array_equal(al.numpy()[0][:2], [1, 2])
    assert int(ln.numpy()[0]) == 2

    rows, lens = sequence_ops.im2sequence(t(r(1, 1, 4, 4)), 2, 2)
    assert rows.shape == [1, 4, 4] and int(lens.numpy()[0]) == 4

    vc = sequence_ops.var_conv_2d(t(r(2, 1, 4, 4)), t(np.array([4, 2])),
                                  t(np.array([4, 3])), t(r(2, 1, 3, 3)))
    assert vc.shape == [2, 2, 4, 4]
    # masked region beyond valid extent is zero
    assert float(np.abs(vc.numpy()[1, :, 2:, :]).max()) == 0.0

    mm = sequence_ops.match_matrix_tensor(
        t(r(2, 3, 4)), t(np.array([3, 2])), t(r(2, 5, 4)),
        t(np.array([5, 4])), t(r(4, 2, 4)))
    assert mm.shape == [2, 2, 3, 5]


def test_lod_facade_roundtrip():
    from paddle_tpu.core.lod import LoDTensor, create_lod_tensor
    lt = create_lod_tensor(np.arange(10, dtype=np.float32).reshape(5, 2),
                           [[2, 3]])
    assert lt.lod() == [[0, 2, 5]]
    assert lt.recursive_sequence_lengths() == [[2, 3]]
    dense, lens = lt.to_padded()
    back = LoDTensor.from_padded(dense, lens)
    np.testing.assert_allclose(back.numpy(), lt.numpy())
    assert back.lod() == [[0, 2, 5]]
    # invalid lod rejected
    with pytest.raises(ValueError):
        lt.set_lod([[1, 2]])

    # lod_reset + sequence_reshape on the facade
    reset = sequence_ops.lod_reset(lt, target_lod=[0, 1, 5])
    assert reset.lod() == [[0, 1, 5]]
    with pytest.raises(ValueError):
        sequence_ops.lod_reset(lt, target_lod=[0, 2])
    rs = sequence_ops.sequence_reshape(lt, 1)
    assert rs.shape[0] == 10 and rs.recursive_sequence_lengths() == [[4, 6]]

    # array <-> lod conversions
    arr = array_ops.lod_tensor_to_array(lt)
    assert len(arr) == 2 and arr[0].shape == [2, 2]
    lt2 = array_ops.array_to_lod_tensor(arr, t(np.array([2, 3])))
    np.testing.assert_allclose(lt2.numpy(), lt.numpy())
    assert lt2.lod() == [[0, 2, 5]]
    full, sizes = array_ops.tensor_array_to_tensor(arr, axis=0)
    assert full.shape == [5, 2]
    np.testing.assert_array_equal(sizes.numpy(), [2, 3])


def test_rnn_units():
    B, D = 3, 4
    x = r(B, 3 * D)
    hp = r(B, D)
    w = r(D, 3 * D)
    h, rhp, g = rnn_unit_ops.gru_unit(t(x), t(hp), t(w))

    def sig(v):
        return 1 / (1 + np.exp(-v))
    uh = x[:, :2 * D] + hp @ w[:, :2 * D]
    u, rr = sig(uh[:, :D]), sig(uh[:, D:])
    c = np.tanh(x[:, 2 * D:] + (rr * hp) @ w[:, 2 * D:].reshape(D, D))
    np.testing.assert_allclose(h.numpy(), u * (c - hp) + hp, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(rhp.numpy(), rr * hp, rtol=1e-4, atol=1e-4)

    x4, cp = r(B, 4 * D), r(B, D)
    c2, h2 = rnn_unit_ops.lstm_unit(t(x4), t(cp), forget_bias=1.0)
    i, gg, f, o = np.split(x4, 4, 1)
    cref = sig(f + 1.0) * cp + sig(i) * np.tanh(gg)
    np.testing.assert_allclose(c2.numpy(), cref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(h2.numpy(), sig(o) * np.tanh(cref),
                               rtol=1e-4, atol=1e-4)

    T, P = 5, 3
    proj, cell = rnn_unit_ops.lstmp(t(r(B, T, 4 * D)), t(r(P, 4 * D)),
                                    t(r(D, P)))
    assert proj.shape == [B, T, P] and cell.shape == [B, T, D]

    out = rnn_unit_ops.multi_gru(t(r(B, T, 4)),
                                 [r(4, 3 * D), r(4, 3 * D)],
                                 [r(D, 3 * D), r(D, 3 * D)])
    assert out.shape == [B, T, 2 * D]

    hs, h, c = rnn_unit_ops.attention_lstm(
        t(r(B, T, D)), t(np.array([5, 3, 4])), t(r(2 * D, 1)),
        t(r(2 * D, 4 * D)), t(r(4 * D)))
    assert hs.shape == [B, T, D] and np.isfinite(hs.numpy()).all()

    ids = rng.randint(0, 7, (B, T))
    hs, h, c = rnn_unit_ops.fused_embedding_fc_lstm(
        t(ids), t(r(7, 4 * D)), t(r(D, 4 * D)), t(r(4 * D)))
    assert hs.shape == [B, T, D]


def test_optimizer_tail_round3():
    import jax.numpy as jnp
    import jax
    p = jnp.asarray(np.array([1.0, -2.0], np.float32))
    g = jnp.asarray(np.array([0.5, 0.5], np.float32))
    out = optimizer_ops.proximal_gd_step(p, g, 0.1, l1=1.0, l2=0.1)
    prox = np.array([0.95, -2.05])
    ref = np.sign(prox) * np.maximum(np.abs(prox) - 0.1, 0) / 1.01
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    newp, m2 = optimizer_ops.proximal_adagrad_step(p, g, jnp.zeros(2), 0.1,
                                                   l1=0.5)
    np.testing.assert_allclose(m2.numpy(), [0.25, 0.25], rtol=1e-6)
    prox = np.asarray(p) - 0.1 * np.asarray(g) / np.sqrt([0.25, 0.25])
    ref = np.sign(prox) * np.maximum(np.abs(prox) - 0.05, 0)
    np.testing.assert_allclose(newp.numpy(), ref, rtol=1e-5)

    d = optimizer_ops.dpsgd_step(p, g, jax.random.PRNGKey(0), 0.1)
    assert d.shape == [2] and np.isfinite(d.numpy()).all()

    z = jnp.zeros(2)
    i64 = lambda v: jnp.asarray(v, jnp.int64)
    s1, s2, s3, nu, na, ona = optimizer_ops.average_accumulates(
        p, z, z, z, i64(0), i64(0), i64(0), average_window=1,
        min_average_window=1)
    # first step: na=1 >= min and >= nu*window → discard into sum_3
    np.testing.assert_allclose(s3.numpy(), np.asarray(p))
    assert int(na.numpy()) == 0 and int(ona.numpy()) == 1


def test_metrics_tail_round3():
    pred = np.array([0, 0, 1, 1, 2])
    lab = np.array([0, 1, 1, 1, 2])
    miou, wrong, correct = metrics_ops.mean_iou(t(pred), t(lab), 3)
    np.testing.assert_allclose(float(miou.numpy()),
                               np.mean([0.5, 2 / 3, 1.0]), rtol=1e-5)

    met, st = metrics_ops.precision_recall(t(np.array([0, 1, 1])),
                                           t(np.array([0, 1, 0])), 2)
    # class0: p=1, r=1/2; class1: p=1/2, r=1 → macro p .75 r .75
    np.testing.assert_allclose(met.numpy()[:2], [0.75, 0.75], rtol=1e-5)
    assert st.shape == [2, 3]

    p, rr, f1, ni, nl, nc = metrics_ops.chunk_eval(
        t(np.array([[0, 1, 2, 0]])), t(np.array([[0, 1, 2, 2]])), 1)
    assert (float(p.numpy()), float(rr.numpy())) == (0.5, 1.0)
    assert (int(ni.numpy()), int(nl.numpy()), int(nc.numpy())) == (2, 1, 1)

    pos, neg, neu = metrics_ops.positive_negative_pair(
        t(np.array([0.9, 0.5, 0.3], np.float32)),
        t(np.array([2, 1, 0], np.float32)), t(np.array([1, 1, 1])))
    assert (float(pos.numpy()), float(neg.numpy()),
            float(neu.numpy())) == (3.0, 0.0, 0.0)

    det = np.array([[1, 0.9, 0, 0, 2, 2]], np.float32)
    gt = np.array([[1, 0, 0, 2, 2, 0]], np.float32)
    assert float(metrics_ops.detection_map(t(det), t(gt), 2).numpy()) == 1.0


def test_quant_tail_round3():
    x = r(3, 4)
    q = quant_ops.quantize(t(x), 127.0)
    dq = quant_ops.dequantize(q, 127.0)
    np.testing.assert_allclose(dq.numpy(), x, atol=1 / 127)
    rq = quant_ops.requantize(q, 127.0, 63.0)
    assert rq.numpy().dtype == np.int32

    w8 = rng.randint(-127, 128, (3, 4)).astype(np.int8)
    d = quant_ops.dequantize_abs_max(t(w8.astype(np.int32)), 2.0, 127.0)
    np.testing.assert_allclose(d.numpy(), w8 * 2.0 / 127.0, rtol=1e-6)

    table = np.exp2(np.arange(128)).astype(np.float32)
    dl = quant_ops.dequantize_log(t(np.array([3, -2], np.int32)), t(table))
    np.testing.assert_allclose(dl.numpy(), [8.0, -np.exp2(126)])

    scales = np.array([1.0, 2.0], np.float32)
    fc = quant_ops.fake_channel_wise_dequantize_max_abs(
        t(np.array([[127, 127], [64, 64]], np.int32).T), t(scales),
        quant_bits=8, quant_axis=0)
    np.testing.assert_allclose(fc.numpy()[:, 0], [1.0, 2.0], rtol=1e-5)
    np.testing.assert_allclose(fc.numpy()[:, 1],
                               [64 / 127, 2.0 * 64 / 127], rtol=1e-5)

    qq, sc, it = quant_ops.fake_quantize_range_abs_max(
        t(np.array([1.0, -3.0], np.float32)), t(np.float32(2.0)), iter=0)
    np.testing.assert_allclose(float(sc.numpy()), 3.0)
    assert int(it.numpy()) == 1

    fi = quant_ops.fake_init([2, 3], 0.0)
    assert fi.shape == [2, 3]


def test_hash_and_misc_extra():
    h1 = extra_ops.hash_op(t(np.array([[1, 2], [3, 4]])), 1000, 2)
    h2 = extra_ops.hash_op(t(np.array([[1, 2], [3, 4]])), 1000, 2)
    assert h1.shape == [2, 2]
    np.testing.assert_array_equal(h1.numpy(), h2.numpy())
    assert (h1.numpy() >= 0).all() and (h1.numpy() < 1000).all()
    assert not (h1.numpy()[0] == h1.numpy()[1]).all()

    ph = extra_ops.pyramid_hash(t(np.array([[1, 2, 3, 4]])), t(r(50, 6)),
                                min_win=2, max_win=3)
    assert ph.shape == [1, 4, 6]

    u, idx, cnt = extra_ops.unique_with_counts(t(np.array([2, 1, 2, 3])))
    np.testing.assert_array_equal(u.numpy(), [1, 2, 3])
    np.testing.assert_array_equal(cnt.numpy(), [1, 2, 1])

    out = extra_ops.py_func(lambda a: a * 2, t(np.ones(3, np.float32)))
    np.testing.assert_allclose(out.numpy(), 2 * np.ones(3))

    sf = extra_ops.similarity_focus(t(r(1, 3, 4, 4)), 1, [0])
    assert set(np.unique(sf.numpy())) <= {0.0, 1.0}

    ra = extra_ops.rank_attention(t(r(2, 3)),
                                  t(np.array([[1, 0, 1], [2, 0, 2]])),
                                  t(r(9 * 3, 4)), max_rank=3)
    assert ra.shape == [2, 4]

    rows, lw, im = extra_ops.filter_by_instag(
        t(r(3, 2)), t(np.array([[1], [2], [3]])), t(np.array([2])))
    assert rows.shape == [1, 2] and im.numpy().ravel().tolist() == [1]

    info = np.array([[0, 0, 0, 0, 0], [1, 0, 0, 2, 3], [2, 1, 1, 0, 0],
                     [3, 1, 1, 0, 0]], np.int64)
    ch, leaf = extra_ops.tdm_child(t(np.array([1])), t(info), 2)
    np.testing.assert_array_equal(ch.numpy()[0], [2, 3])
    np.testing.assert_array_equal(leaf.numpy()[0], [1, 1])

    outs, labels, mask = extra_ops.tdm_sampler(
        t(np.array([2, 3])), t(info[:, 2:3].repeat(2, 1)[:, :1]),
        [t(np.array([1])), t(np.array([2, 3]))], [0, 1])
    assert outs.shape[0] == 2

    # nce decreases for the true class direction + grad flows
    paddle.seed(5)
    xn = t(r(4, 8), stop_gradient=False)
    cost = extra_ops.nce(xn, t(np.array([1, 2, 0, 3])), t(r(10, 8)),
                         num_neg_samples=5)
    assert cost.shape == [4]
    cost.sum().backward()
    assert np.isfinite(xn.grad.numpy()).all()

    hs = extra_ops.hierarchical_sigmoid(t(r(3, 8)), t(r(7, 8)),
                                        t(np.array([0, 3, 7])),
                                        num_classes=8)
    assert hs.shape == [3, 1] and (hs.numpy() > 0).all()

    x1, x2 = r(1, 3, 5, 5), r(1, 3, 5, 5)
    c = extra_ops.correlation(t(x1), t(x2), max_displacement=1)
    assert c.shape == [1, 9, 5, 5]
    np.testing.assert_allclose(c.numpy()[0, 4], (x1[0] * x2[0]).mean(0),
                               rtol=1e-4, atol=1e-5)

    g = r(1, 8, 2, 3, 3)
    guide = rng.rand(1, 5, 5).astype(np.float32)
    bs = extra_ops.bilateral_slice(t(x1), t(g), t(guide), has_offset=True)
    assert bs.shape == [1, 2, 5, 5] and np.isfinite(bs.numpy()).all()

    tc = extra_ops.tree_conv(t(r(1, 4, 3)),
                             t(np.array([[[1, 2], [1, 3], [0, 0]]])),
                             t(r(3, 5, 3)))
    assert tc.shape == [1, 4, 5]

    full, sc = extra_ops.beam_search_decode(
        t(np.array([[[1, 2]], [[3, 4]], [[5, 6]]])),
        t(np.array([[[0, 0]], [[0, 0]], [[1, 0]]])),
        t(np.zeros((1, 2), np.float32)))
    np.testing.assert_array_equal(full.numpy()[:, 0, 0], [1, 4, 5])


def test_fused_tail_round3():
    x, w, b = r(2, 3, 4), r(4, 5), r(5)
    out = fused_ops.fc(t(x), t(w), t(b), in_num_col_dims=2,
                       activation="relu")
    ref = np.maximum(x @ w + b, 0)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-2, atol=1e-2)

    img = r(1, 3, 6, 6)
    cf = fused_ops.conv2d_fusion(t(img), t(r(4, 3, 3, 3)), t(r(4)),
                                 padding=1)
    assert cf.shape == [1, 4, 6, 6] and (cf.numpy() >= 0).all()

    ic = fused_ops.conv2d_inception_fusion(
        t(img), [t(r(2, 3, 1, 1)), t(r(2, 3, 3, 3))])
    assert ic.shape == [1, 4, 6, 6]

    rm = t(np.zeros(3, np.float32))
    rv = t(np.ones(3, np.float32))
    ba = fused_ops.fused_bn_add_activation(t(r(2, 3, 4, 4)), t(r(2, 3, 4,
                                           4)), rm, rv, t(np.ones(3,
                                           np.float32)), t(np.zeros(3,
                                           np.float32)))
    assert (ba.numpy() >= 0).all()

    e1 = fused_ops.fused_embedding_eltwise_layernorm(
        [t(np.array([[0, 1]])), t(np.array([[1, 0]]))],
        [t(r(4, 6)), t(r(4, 6))], t(np.ones(6, np.float32)),
        t(np.zeros(6, np.float32)))
    out = np.asarray(e1.numpy())
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)

    fl = fused_ops.fused_fc_elementwise_layernorm(
        t(r(2, 4)), t(r(4, 6)), t(r(2, 6)))
    np.testing.assert_allclose(fl.numpy().mean(-1), 0, atol=1e-5)

    sq = fused_ops.fusion_seqconv_eltadd_relu(
        t(r(1, 4, 2)), t(np.array([4])), t(r(6, 3)), t(r(3)))
    assert (sq.numpy() >= 0).all()

    fe = fused_ops.fusion_seqexpand_concat_fc(
        [t(r(2, 3, 4)), t(np.array([[1.0, 2.0], [3.0, 4.0]],
                          np.float32))],
        t(np.array([3, 3])), t(r(6, 5)))
    assert fe.shape == [2, 3, 5]

    ftc = fused_ops.fusion_transpose_flatten_concat(
        [t(r(2, 3, 4)), t(r(2, 3, 4))], (0, 2, 1), 1, 1)
    assert ftc.shape == [2, 24]

    # multihead_matmul == manual attention oracle
    B, T, D, H = 1, 3, 4, 2
    xx = r(B, T, D)
    qkvw = r(D, 3 * D)
    qkvb = np.zeros(3 * D, np.float32)
    mh = fused_ops.multihead_matmul(t(xx), t(qkvw), t(qkvb), num_heads=H,
                                    scale=1.0)
    qkv = xx @ qkvw
    q, k, v = np.split(qkv, 3, -1)

    def heads(a):
        return a.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
    qh, kh, vh = heads(q), heads(k), heads(v)
    att = qh @ kh.transpose(0, 1, 3, 2)
    att = np.exp(att - att.max(-1, keepdims=True))
    att /= att.sum(-1, keepdims=True)
    ref = (att @ vh).transpose(0, 2, 1, 3).reshape(B, T, D)
    np.testing.assert_allclose(mh.numpy(), ref, rtol=2e-2, atol=2e-2)

    # first two features are show/click counts (log-transformed by CVM):
    # must be positive, like the reference's usage
    sp = fused_ops.fusion_seqpool_cvm_concat(
        [t(r(2, 3, 4, lo=0.5, hi=2.0))], [t(np.array([2, 3]))],
        t(np.ones((2, 2), np.float32)))
    assert np.isfinite(sp.numpy()).all()


def test_vision_tail_round3():
    # deformable conv with zero offsets == plain conv (torch oracle)
    x = r(1, 4, 8, 8)
    w = r(6, 4, 3, 3)
    off = np.zeros((1, 18, 8, 8), np.float32)
    out = vision_ops.deformable_conv(t(x), t(off), t(w), padding=1)
    ref = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(w),
                                     padding=1).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-2, atol=1e-2)

    # fractional offset: 1x1 kernel dy=0.5 → bilinear mean of vertical pair
    x1 = r(1, 1, 4, 4)
    off = np.zeros((1, 2, 4, 4), np.float32)
    off[0, 0] = 0.5
    o = vision_ops.deformable_conv(t(x1), t(off),
                                   t(np.ones((1, 1, 1, 1), np.float32)))
    ref = 0.5 * x1[0, 0] + 0.5 * np.vstack([x1[0, 0, 1:],
                                            np.zeros((1, 4))])
    np.testing.assert_allclose(o.numpy()[0, 0], ref, rtol=1e-4, atol=1e-5)

    # grads flow to x, offset, weight
    xg = t(r(1, 2, 6, 6), stop_gradient=False)
    og = t(r(1, 18, 6, 6) * 0.3, stop_gradient=False)
    wg = t(r(3, 2, 3, 3), stop_gradient=False)
    vision_ops.deformable_conv(xg, og, wg, padding=1).sum().backward()
    for v in (xg, og, wg):
        assert np.isfinite(v.grad.numpy()).all()
        assert float(np.abs(v.grad.numpy()).sum()) > 0

    # psroi: uniform input → every bin equals the value
    xc = np.ones((1, 8, 6, 6), np.float32)
    ps = vision_ops.psroi_pool(t(xc), t(np.array([[0., 0., 4., 4.]],
                               np.float32)), t(np.array([1])), 2, 1.0, 2, 2)
    np.testing.assert_allclose(ps.numpy(), np.ones((1, 2, 2, 2)))

    pr = vision_ops.prroi_pool(t(r(1, 3, 8, 8)),
                               t(np.array([[1., 1., 5., 5.]], np.float32)),
                               t(np.array([1])), 2, 2)
    assert pr.shape == [1, 3, 2, 2]

    rc = vision_ops.random_crop(t(r(2, 3, 10, 10)), [6, 6])
    assert rc.shape == [2, 3, 6, 6]

    sp = vision_ops.spp(t(r(2, 3, 8, 8)), 2)
    assert sp.shape == [2, 15]

    dp = vision_ops.deformable_psroi_pooling(
        t(xc), t(np.array([[0., 0., 4., 4.]], np.float32)),
        t(np.zeros((1, 2, 2, 2), np.float32)), t(np.array([1])),
        output_channels=2, pooled_height=2, pooled_width=2)
    assert dp.shape == [1, 2, 2, 2]


def test_detection_tail_round3():
    # anchor_generator against the reference formula
    a, v = detection_ops.anchor_generator(t(r(1, 8, 2, 2)),
                                          anchor_sizes=[64.],
                                          aspect_ratios=[1.0],
                                          stride=[16., 16.])
    assert a.shape == [2, 2, 1, 4]
    # cell (0,0): ctr = 0.5*15 = 7.5; w = h = 4*16=64 → [-24, -24, 39, 39]
    np.testing.assert_allclose(a.numpy()[0, 0, 0],
                               [7.5 - 31.5, 7.5 - 31.5, 7.5 + 31.5,
                                7.5 + 31.5])

    outs, restore = detection_ops.distribute_fpn_proposals(
        t(np.array([[0., 0., 20., 20.], [0., 0., 200., 200.]],
          np.float32)), 2, 5, 4, 224)
    assert [o.shape[0] for o in outs] == [1, 1, 0, 0]
    np.testing.assert_array_equal(restore.numpy().ravel(), [0, 1])

    anchors, _ = detection_ops.anchor_generator(
        t(np.zeros((1, 8, 4, 4), np.float32)),
        anchor_sizes=[32., 64., 128.], aspect_ratios=[1.0])
    scores = rng.rand(1, 3, 4, 4).astype(np.float32)
    deltas = (rng.randn(1, 12, 4, 4) * 0.1).astype(np.float32)
    props, pscores, pnum = detection_ops.generate_proposals(
        t(scores), t(deltas), t(np.array([[64., 64., 1.]], np.float32)),
        anchors)
    assert props.shape[0] == int(pnum.numpy()[0]) > 0
    # scores sorted descending
    ss = pscores.numpy()
    assert (np.diff(ss) <= 1e-6).all()

    gt = np.array([[10., 10., 30., 30.]], np.float32)
    li, si, tl, tb, iw = detection_ops.rpn_target_assign(anchors, t(gt))
    assert len(li.numpy()) >= 1 and tb.shape[1] == 4

    out, wgt = detection_ops.target_assign(
        t(r(2, 4, 3)), t(np.array([[0, -1], [2, 1]])))
    assert out.shape == [2, 2, 3]
    np.testing.assert_array_equal(wgt.numpy(), [[1, 0], [1, 1]])

    # yolov3_loss: grads flow, loss decreases along negative gradient
    N, na, nc, H = 1, 3, 4, 8
    xv = t(r(N, na * (5 + nc), H, H) * 0.1, stop_gradient=False)
    gt_box = np.zeros((N, 3, 4), np.float32)
    gt_box[0, 0] = [0.5, 0.5, 0.3, 0.4]
    gt_lab = np.zeros((N, 3), np.int64)
    gt_lab[0, 0] = 2
    kw = dict(anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2],
              class_num=nc, downsample_ratio=32)
    loss = detection_ops.yolov3_loss(xv, t(gt_box), t(gt_lab), **kw)
    assert loss.shape == [N]
    loss.sum().backward()
    g = xv.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    stepped = xv.numpy() - 0.05 * g
    loss2 = detection_ops.yolov3_loss(t(stepped), t(gt_box), t(gt_lab),
                                      **kw)
    assert float(loss2.numpy().sum()) < float(loss.numpy().sum())

    rd = detection_ops.retinanet_detection_output(
        [t(r(48, 4) * 0.1)], [t(rng.rand(48, 3).astype(np.float32))],
        [anchors], t(np.array([[64., 64., 1.]], np.float32)))
    assert rd.shape[1] == 6

    pb = detection_ops.polygon_box_transform(t(r(1, 2, 2, 3)))
    assert pb.shape == [1, 2, 2, 3]


def test_static_print_assert():
    import paddle_tpu.static as S
    out = S.Print(t(np.arange(3.0)), message="test")
    np.testing.assert_allclose(out.numpy(), np.arange(3.0))
    S.Assert(t(True))
    with pytest.raises(AssertionError):
        S.Assert(t(False), data=[t(np.arange(2.0))])


def test_selected_rows_split():
    from paddle_tpu.core.selected_rows import SelectedRows, \
        split_selected_rows
    import jax.numpy as jnp
    sr = SelectedRows(np.array([1, 5, 8]), jnp.asarray(r(3, 2)), 10)
    parts = split_selected_rows(sr, [4, 6])
    assert list(parts[0].rows) == [1] and list(parts[1].rows) == [1, 4]
    assert parts[0].height == 4 and parts[1].height == 6
