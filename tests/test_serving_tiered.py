"""Hierarchical KV-cache tiering: the host-RAM tier behind
PrefixCacheIndex (paddle_tpu/inference/serving/host_tier.py + the
demote/promote paths in PagedKVCache, ISSUE 16).

The load-bearing pins (docs/serving.md "Hierarchical KV-cache
tiering"):

- tiering is INVISIBLE to outputs: a prefix that round-trips
  device -> host -> device is bitwise-identical to a device hit and to
  cache-off, for greedy decode AND seeded stochastic sampling (both
  engines pinned to the chunked path, the PR-11 parity contract);
- promotion is fault-bounded: a killed promotion (injected
  kill_promotion), a deadline (promote_timeout_s) or a torn host
  payload (sha256 mismatch) degrades to re-prefill of the missing
  suffix — the request finishes with correct output, never wedges,
  and the reqtrace timeline pairs every tiered prefix_match with a
  promote or promote_abort (check_causality invariants 6/7);
- a timeout leaves the entry host-resident (retryable); an integrity
  failure drops the subtree (never promoted);
- scrub-taint crosses tiers: a taint raised while descendants are
  host-resident POISONS the spilled copies (dropped, counted, never
  promoted), and a tainted block never reaches the host store;
- peer prefix fetch is transactional: a replica missing a prefix pulls
  it from a peer bitwise-intact, and a digest mismatch or a full pool
  aborts with the destination untouched;
- batched demotion selects the exact victim sequence the
  one-at-a-time loop would (the `pending` contract of
  lru_demotable);
- zero-leak spans tiers: cross-tier check_integrity stays clean and
  clear_prefix_cache reconciles blocks_allocated == blocks_freed with
  an empty host store.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.inference.serving import (EngineConfig, LLMEngine,
                                          PagedKVCache, PrefixCacheIndex,
                                          ReplicaSet, RouterConfig,
                                          SamplingParams)
from paddle_tpu.obs.reqtrace import check_causality
from paddle_tpu.testing.faults import ServingFaultInjector

VOCAB = 97
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def recording():
    """Fresh, enabled process ring per test (the promote/demote event
    pairing assertions read it); always disarmed after."""
    obs.reqtrace.clear()
    obs.reqtrace.enable()
    yield
    obs.reqtrace.disarm()
    obs.reqtrace.enable()
    obs.reqtrace.clear()


def _engine(model, faults=None, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 20)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("decode_chunk_size", 4)
    kw.setdefault("enable_prefix_cache", True)
    kw.setdefault("host_tier_blocks", 64)
    return LLMEngine.from_model(model, EngineConfig(**kw),
                                faults=faults or ServingFaultInjector(""))


def _drain(eng, max_steps=600):
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps <= max_steps, "engine failed to drain"


def _run_sequential(eng, prompts, params_fn):
    """One request at a time, fully drained before the next arrives —
    the deterministic arrival shape that makes the demote/promote
    schedule identical across the compared engines."""
    out = {}
    for i, p in enumerate(prompts):
        r = eng.add_request(p, params_fn(i))
        _drain(eng)
        out[i] = list(eng.get_request(r).output_ids)
    return out


def _tiering_prompts(seed=0):
    """4 templates x 24 tokens revisited after enough churn that a
    20-block pool must demote the early templates to host — the
    revisits (last two prompts) then promote them back."""
    rng = np.random.RandomState(seed)
    tpls = [rng.randint(1, VOCAB, (24,), dtype=np.int32)
            for _ in range(4)]
    order = [0, 1, 2, 3, 0, 1]
    return [np.concatenate(
                [tpls[t], rng.randint(1, VOCAB, (4,), dtype=np.int32)])
            for t in order]


def _audit_clean(cache):
    cache.check_integrity()
    cache.clear_prefix_cache()
    r = cache.check_integrity()
    assert r["leaked"] == 0 and r["host_leaked"] == 0 \
        and r["host_orphans"] == 0
    s = cache.stats()
    assert s["blocks_allocated"] == s["blocks_freed"]
    assert len(cache.host_tier) == 0


# ------------------------------------------------------ bitwise parity

def test_demote_promote_bitwise_parity_greedy(model):
    prompts = _tiering_prompts()
    params = lambda i: SamplingParams(max_tokens=6)  # noqa: E731
    tiered = _engine(model)
    out_t = _run_sequential(tiered, prompts, params)
    ps = tiered.cache.prefix_stats()
    assert ps["tier_demotions"] >= 1, f"no demotion pressure: {ps}"
    assert ps["promote_hit"] >= 1, f"tiering was vacuous: {ps}"
    # the reqtrace timeline carries the tier lifecycle and stays causal
    kinds = {e.kind for e in obs.reqtrace.events()}
    assert {"demote", "promote"} <= kinds, kinds
    dump = obs.reqtrace.dump_payload(
        "test", trace_ids=sorted(obs.reqtrace.traces(
            prefix=f"tr-{tiered.stats.label}-")))
    assert check_causality(dump) == []
    # device-hit reference: same workload, pool big enough that the
    # revisits hit device-resident blocks (no tier round-trip)
    device = _engine(model, num_blocks=64)
    out_d = _run_sequential(device, prompts, params)
    dps = device.cache.prefix_stats()
    assert dps["tier_demotions"] == 0 and dps["hits"] >= 2, dps
    off = _engine(model, enable_prefix_cache=False, host_tier_blocks=0)
    out_o = _run_sequential(off, prompts, params)
    assert out_t == out_d == out_o
    _audit_clean(tiered.cache)


def test_demote_promote_bitwise_parity_stochastic(model):
    # all engines pinned to the CHUNKED path (prefill_chunk_threshold=0)
    # so the first sampled token comes from the in-scan sampler on every
    # side — the PR-11 parity contract; the only difference left is the
    # tier round-trip, which must not change a single seeded draw
    prompts = _tiering_prompts(seed=1)
    params = lambda i: SamplingParams(  # noqa: E731
        max_tokens=6, temperature=0.8, top_k=20, seed=100 + i)
    tiered = _engine(model, prefill_chunk_threshold=0)
    out_t = _run_sequential(tiered, prompts, params)
    ps = tiered.cache.prefix_stats()
    assert ps["tier_demotions"] >= 1 and ps["promote_hit"] >= 1, ps
    device = _engine(model, num_blocks=64, prefill_chunk_threshold=0)
    out_d = _run_sequential(device, prompts, params)
    off = _engine(model, enable_prefix_cache=False, host_tier_blocks=0,
                  prefill_chunk_threshold=0)
    out_o = _run_sequential(off, prompts, params)
    assert out_t == out_d == out_o
    _audit_clean(tiered.cache)


# ----------------------------------------------- degraded promotion

def test_failed_promotion_degrades_to_reprefill(model):
    """kill_promotion cuts the first fill short: the entry stays
    host-resident, the request re-prefills and finishes with the same
    greedy output, and the timeline pairs the tiered prefix_match with
    a promote_abort followed by re-prefill (invariants 6/7)."""
    prompts = _tiering_prompts(seed=2)
    params = lambda i: SamplingParams(max_tokens=6)  # noqa: E731
    faulted = _engine(model, faults=ServingFaultInjector("kill_promotion@0"))
    out_f = _run_sequential(faulted, prompts, params)
    ps = faulted.cache.prefix_stats()
    assert ps["tier_demotions"] >= 1, ps
    assert ps["promote_timeout"] >= 1, \
        f"kill_promotion never landed on a fill: {ps}"
    kinds = [e.kind for e in obs.reqtrace.events()]
    assert "promote_abort" in kinds, set(kinds)
    dump = obs.reqtrace.dump_payload(
        "test", trace_ids=sorted(obs.reqtrace.traces(
            prefix=f"tr-{faulted.stats.label}-")))
    assert check_causality(dump) == []
    off = _engine(model, enable_prefix_cache=False, host_tier_blocks=0)
    out_o = _run_sequential(off, prompts, params)
    assert out_f == out_o
    _audit_clean(faulted.cache)


# -------------------------------------------------- cache-level tiers

def _demoted_chain(host_blocks=8, promote_timeout_s=None):
    """A PagedKVCache whose 4-block template chain has been fully
    demoted to the host tier, with recognizable per-block payloads.
    Returns (cache, tokens, template_blocks)."""
    import jax.numpy as jnp
    cache = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         num_blocks=8, block_size=4,
                         enable_prefix_cache=True,
                         host_tier_blocks=host_blocks,
                         promote_timeout_s=promote_timeout_s)
    ta = np.arange(1, 18, dtype=np.int32)           # 17 tokens, 4 full blocks
    assert cache.allocate_with_prefix("a", ta) == 0
    cache.reserve_slots("a", len(ta))
    blocks = list(cache.block_table("a")[:4])
    kp, vp = cache.pools[0]
    for j, b in enumerate(blocks):                  # distinct payloads
        kp = kp.at[b].set(float(j + 1))
        vp = vp.at[b].set(-float(j + 1))
    cache.pools = ((kp, vp),)
    cache.free("a", cache_tokens=ta)                # 4 retained, evictable
    # two waves of pool pressure demote the whole chain leaf-ward
    cache.allocate("f", 24)                         # 6 blocks: demotes 2
    cache.free("f")
    cache.allocate("g", 32)                         # 8 blocks: demotes 2 more
    cache.free("g")
    assert cache.match_len(ta) == 0
    assert cache.host_match_len(ta) == 16
    assert cache.tier_demotions == 4
    return cache, ta, blocks


def test_cache_promote_roundtrip_is_bitwise():
    cache, ta, _old = _demoted_chain()
    promo = cache.ensure_promoted(ta)
    assert promo["outcomes"] == ["hit"] * 4
    assert promo["promoted_blocks"] == 4
    assert cache.match_len(ta) == 16
    assert len(cache.host_tier) == 0
    # the promoted chain carries the exact spilled bytes
    path, _ = cache.prefix_index.match([int(t) for t in ta[:16]])
    assert len(path) == 4
    kp, vp = cache.pools[0]
    for j, node in enumerate(path):
        assert bool(np.all(np.asarray(kp[node.block]) == float(j + 1)))
        assert bool(np.all(np.asarray(vp[node.block]) == -float(j + 1)))
    _audit_clean(cache)


def test_cache_promote_timeout_is_retryable():
    cache, ta, _old = _demoted_chain(promote_timeout_s=0.0)
    promo = cache.ensure_promoted(ta)
    assert promo["outcomes"] == ["timeout"]
    assert promo["promoted_blocks"] == 0
    assert cache.tier_promotions["timeout"] == 1
    # deadline left the entries host-resident: a retry without the
    # deadline promotes the full chain
    assert cache.host_match_len(ta) == 16
    cache.promote_timeout_s = None
    assert cache.ensure_promoted(ta)["outcomes"] == ["hit"] * 4
    assert cache.match_len(ta) == 16
    _audit_clean(cache)


def test_cache_corrupt_host_block_fails_integrity_and_drops():
    cache, ta, _old = _demoted_chain()
    # flip one byte of the LRU-oldest entry (the leaf-most spill)
    # without updating its digest — the fill must catch it
    assert cache.host_tier.corrupt_oldest()
    promo = cache.ensure_promoted(ta)
    assert promo["outcomes"] == ["hit"] * 3 + ["integrity"]
    assert cache.tier_promotions["integrity"] == 1
    # the torn entry is gone (never promoted); the intact prefix is
    # device-resident and the tail re-prefills
    assert cache.match_len(ta) == 12
    assert cache.host_match_len(ta) == 0
    assert len(cache.host_tier) == 0
    _audit_clean(cache)


def test_taint_poisons_host_copy_and_never_spills():
    """Satellite 1 (the PR-11 scrub pin across tiers): scrub-freeing
    one sharer of a prefix whose descendants were demoted must POISON
    the host copies — dropped immediately, never promoted — while the
    surviving sharer's device blocks are not zeroed under it; tainted
    blocks never reach the host store."""
    import jax.numpy as jnp
    cache = PagedKVCache(num_layers=1, num_heads=1, head_dim=2,
                         num_blocks=8, block_size=4,
                         enable_prefix_cache=True, host_tier_blocks=8)
    ta = np.arange(1, 18, dtype=np.int32)
    assert cache.allocate_with_prefix("a", ta) == 0
    cache.reserve_slots("a", len(ta))
    blocks = list(cache.block_table("a")[:4])
    cache.free("a", cache_tokens=ta)
    # demote the two leaf-most chain blocks host-side
    cache.allocate("f", 24)
    cache.free("f")
    assert cache.tier_demotions == 2
    assert cache.host_tier.stats()["puts"] == 2
    # give the still-device blocks recognizable nonzero KV, then attach
    # two sharers to them
    dev = np.array(blocks[:2])
    cache.pools = tuple((kp.at[dev].set(1.0), vp.at[dev].set(1.0))
                        for kp, vp in cache.pools)
    tb = np.concatenate([ta[:8], [50, 51]]).astype(np.int32)
    tc = np.concatenate([ta[:8], [60, 61]]).astype(np.int32)
    assert cache.allocate_with_prefix("b", tb) == 8
    cache.reserve_slots("b", 2)
    assert cache.allocate_with_prefix("c", tc) == 8
    cache.reserve_slots("c", 2)
    cache.free("b", scrub=True)                     # faulted sharer
    hs = cache.host_tier.stats()
    assert hs["poisoned"] == 2, hs                  # host copies poisoned
    assert len(cache.host_tier) == 0
    assert hs["puts"] == 2, "a tainted block reached the host store"
    # the whole prefix is distrusted on both tiers...
    assert cache.match_len(ta) == 0
    assert cache.host_match_len(ta) == 0
    # ...but c still reads the device blocks: NOT zeroed under it
    assert bool(jnp.all(cache.pools[0][0][dev] == 1.0))
    cache.free("c")                                 # LAST free: scrub
    assert bool(jnp.all(cache.pools[0][0][dev] == 0.0))
    r = cache.check_integrity()
    assert r["leaked"] == 0 and r["stale_tainted"] == 0
    s = cache.stats()
    assert s["blocks_allocated"] == s["blocks_freed"]


def test_lru_demotable_batched_matches_sequential():
    """The `pending` contract: selecting N victims with pending
    accumulation (batched demotion) yields the exact node sequence the
    demote-one-at-a-time loop produces."""
    def build():
        idx = PrefixCacheIndex(block_size=2)
        idx.insert(list(range(1, 9)), [10, 11, 12, 13])     # 4-deep chain
        idx.insert([1, 2, 3, 4, 9, 9], [10, 11, 20])        # branch
        return idx

    batched = build()
    pending, order = set(), []
    while True:
        n = batched.lru_demotable(lambda b: True, pending=pending)
        if n is None:
            break
        pending.add(n)
        order.append(n.block)
    sequential = build()
    order_seq, hid = [], 0
    while True:
        n = sequential.lru_demotable(lambda b: True)
        if n is None:
            break
        order_seq.append(n.block)
        sequential.demote(n, hid)
        hid += 1
    assert order == order_seq
    assert sorted(order) == [10, 11, 12, 13, 20]
    assert batched.audit() == 0 and sequential.audit() == 0


# ---------------------------------------------------- peer prefix fetch

def _fleet(model, num_replicas=2, **ekw):
    ekw.setdefault("block_size", 4)
    ekw.setdefault("num_blocks", 32)
    ekw.setdefault("max_num_seqs", 4)
    ekw.setdefault("decode_chunk_size", 4)
    ekw.setdefault("enable_prefix_cache", True)
    ekw.setdefault("host_tier_blocks", 32)
    rc = RouterConfig(num_replicas=num_replicas, balance="round_robin",
                      peer_prefix_fetch=True, backoff_base=0.01,
                      backoff_max=0.05, backoff_jitter=0.0)
    return ReplicaSet.from_model(model, rc, engine_config=EngineConfig(**ekw))


def _drain_fleet(rs, max_steps=600):
    steps = 0
    while rs.has_unfinished():
        rs.step()
        steps += 1
        assert steps <= max_steps


def test_peer_fetch_fills_cold_replica_bitwise(model):
    rng = np.random.RandomState(7)
    tpl = rng.randint(1, VOCAB, (24,), dtype=np.int32)
    leader = np.concatenate([tpl, rng.randint(1, VOCAB, (4,),
                                              dtype=np.int32)])
    follower = np.concatenate([tpl, rng.randint(1, VOCAB, (4,),
                                                dtype=np.int32)])
    params = SamplingParams(max_tokens=6)
    rs = _fleet(model)
    r0 = rs.add_request(leader, params)             # round-robin: replica 0
    _drain_fleet(rs)
    r1 = rs.add_request(follower, params)           # replica 1: cold, pulls
    _drain_fleet(rs)
    ms = rs.migrator.stats()
    assert ms["prefix_fetches"] >= 1, ms
    assert ms["prefix_aborted"] == 0 and ms["prefix_bytes"] > 0, ms
    assert {rs.get_request(r0).replica, rs.get_request(r1).replica} \
        == {0, 1}
    # the peer-fetched blocks decode bitwise like a local prefill
    off = _engine(model, enable_prefix_cache=False, host_tier_blocks=0,
                  num_blocks=32)
    out_off = _run_sequential(off, [leader, follower],
                              lambda i: params)
    assert list(rs.get_request(r0).tokens) == out_off[0]
    assert list(rs.get_request(r1).tokens) == out_off[1]
    kinds = {e.kind for e in obs.reqtrace.events()}
    assert "peer_fetch" in kinds, kinds
    for audit in rs.check_integrity().values():
        assert audit is None or (audit["leaked"] == 0
                                 and audit["host_leaked"] == 0)


def test_peer_fetch_aborts_atomically(model):
    """Both abort legs leave the destination untouched: a digest
    mismatch raises out of admit_prefix before any block is claimed,
    and a full destination pool aborts the transactional pull
    (prefix_aborted) so the request degrades to re-prefill."""
    rng = np.random.RandomState(8)
    tpl = rng.randint(1, VOCAB, (24,), dtype=np.int32)
    params = SamplingParams(max_tokens=4)
    rs = _fleet(model, num_blocks=16)
    src, dst = rs.replicas[0], rs.replicas[1]
    # warm the donor directly
    src.engine.add_request(tpl, params)
    _drain(src.engine)
    snap = src.export_prefix(tpl)
    assert snap is not None and len(snap["blocks"]) >= 1
    # leg 1: tamper one payload byte — every digest is re-verified
    # before a single block is claimed
    free_before = dst.engine.cache.num_free()
    payload0, _digest0 = snap["blocks"][0]
    payload0[0][0].flat[0] += 1.0                   # layer-0 K, one value
    with pytest.raises(ValueError):
        dst.admit_prefix(tpl, snap["blocks"])
    assert dst.engine.cache.num_free() == free_before
    dst.engine.cache.check_integrity()
    # leg 2: fill the destination pool so the pull cannot fit — the
    # coordinator aborts and counts it, destination still untouched
    hog = rng.randint(1, VOCAB, (48,), dtype=np.int32)  # 12 of 16 blocks
    dst.engine.add_request(hog, SamplingParams(max_tokens=8))
    dst.engine.step()
    assert rs.migrator.fetch_prefix(src, dst, "rq-abort", "tr-abort",
                                    tpl) is None
    ms = rs.migrator.stats()
    assert ms["prefix_aborted"] >= 1, ms
    dst.engine.cache.check_integrity()


# ------------------------------------------------- checker invariants

def _ev(seq, kind, tid="t0", **attrs):
    return {"seq": seq, "ts": float(seq), "trace_id": tid,
            "request_id": "r0", "kind": kind, "attrs": attrs}


def test_checker_tiering_invariants_on_synthetic_dumps():
    # clean: tiered match resolved by promote before tokens flow
    clean = {"complete": True, "events": [
        _ev(0, "engine_admit", engine="e0", arrival=1.0),
        _ev(1, "prefix_match", cached_tokens=0, host_tokens=8),
        _ev(2, "promote", blocks=2, tokens=8),
        _ev(3, "scheduled"),
        _ev(4, "prefill", tokens=12),
        _ev(5, "first_token"),
        _ev(6, "finish", reason="length"),
    ]}
    assert check_causality(clean) == []
    # invariant 6: tokens while matched blocks are still host-resident
    unresolved = {"complete": True, "events": [
        _ev(0, "engine_admit", engine="e0", arrival=1.0),
        _ev(1, "prefix_match", cached_tokens=0, host_tokens=8),
        _ev(2, "scheduled"),
        _ev(3, "prefill", tokens=12),
        _ev(4, "first_token"),
        _ev(5, "finish", reason="length"),
    ]}
    v = check_causality(unresolved)
    assert any("host-resident" in x for x in v), v
    # invariant 7: a degraded promotion must be followed by re-prefill
    # progress or a terminal — a bare promote_abort is a wedged request
    wedged = {"complete": True, "events": [
        _ev(0, "engine_admit", engine="e0", arrival=1.0),
        _ev(1, "prefix_match", cached_tokens=0, host_tokens=8),
        _ev(2, "promote_abort", outcome="timeout"),
    ]}
    v = check_causality(wedged)
    assert any("wedged" in x for x in v), v
    # ...and promote_abort -> prefill -> terminal is the healthy
    # degraded path
    degraded = {"complete": True, "events": [
        _ev(0, "engine_admit", engine="e0", arrival=1.0),
        _ev(1, "prefix_match", cached_tokens=0, host_tokens=8),
        _ev(2, "promote_abort", outcome="integrity"),
        _ev(3, "scheduled"),
        _ev(4, "prefill", tokens=12),
        _ev(5, "first_token"),
        _ev(6, "finish", reason="length"),
    ]}
    assert check_causality(degraded) == []


# ------------------------------------------------------- chaos smoke

@pytest.mark.slow
def test_chaos_tiering_runner_cpu():
    """tools/chaos_serve.py --tiering smoke: the seeded tier-fault
    schedule drains with zero lost requests, zero leaks on both tiers
    and bitwise survivors (exit 0)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_serve
    rc = chaos_serve.main(["--tiering", "--seed", "0"])
    assert rc == 0
