"""Tier-1 tests for the jaxcost static cost model + budget gate.

Four layers:

  1. cost fixtures    — hand-computed FLOPs/bytes/peak/comm on crafted
                        jaxprs (matmul chain, scan carry, psum tree,
                        cond branches) asserted EXACTLY;
  2. donation audit   — a toy true positive, the BatchNorm-buffers
                        catch that motivated TrainStep's donate set,
                        and the registry's zero-unsuppressed gate;
  3. donation safety  — donated vs undonated TrainStep twins produce
                        bitwise-identical losses and parameters;
  4. budget gate      — tools/jaxcost.py --budget check passes on the
                        committed jaxcost_budget.json and exits nonzero
                        when a budget is exceeded past tolerance.

Also pins the hlo_bytes single-source contract: tools/hlo_bytes.py is a
wrapper with no byte-accounting logic of its own.
"""
import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis import hlo_bytes as hb
from paddle_tpu.analysis import jaxcost
from paddle_tpu.analysis.liveness import peak_live_bytes

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
JAXCOST_CLI = REPO / "tools" / "jaxcost.py"
BUDGET_FILE = REPO / "jaxcost_budget.json"

F32 = jnp.float32


# ---------------------------------------------------------- cost fixtures
def test_matmul_chain_exact():
    """(a@b)@c with a[8,16] b[16,32] c[32,4] f32.

    flops: 2*8*32*16 + 2*8*4*32 = 8192 + 2048 = 10240
    read:  a 512 + b 2048 + ab 1024 + c 512   = 4096
    write: ab 1024 + out 128                  = 1152
    peak:  entry 3072 live + ab 1024          = 4096
    """
    a = jnp.zeros((8, 16), F32)
    b = jnp.zeros((16, 32), F32)
    c = jnp.zeros((32, 4), F32)
    cost = jaxcost.estimate_fn(lambda a, b, c: jnp.dot(jnp.dot(a, b), c),
                               a, b, c, name="chain")
    assert cost.flops == 10240
    assert cost.bytes_read == 4096
    assert cost.bytes_written == 1152
    assert cost.peak_bytes == 4096
    assert cost.comm_bytes == 0
    assert cost.by_primitive["dot_general"]["count"] == 2


def test_scan_carry_exact():
    """scan of carry[4,4] @ W over length 5, stacking ys.

    flops: 2*4*4*4 per trip * 5      = 640
    read:  (carry 64 + W 64) * 5     = 640
    write: new-carry 64 * 5          = 320
    peak:  entry (c0+W) 128 + scan outs (carry 64 + ys 320)
           + body extra 64           = 576
    """
    W = jnp.zeros((4, 4), F32)

    def body(carry, _):
        new = jnp.dot(carry, W)
        return new, new

    def prog(c0):
        return jax.lax.scan(body, c0, None, length=5)

    cost = jaxcost.estimate_fn(prog, jnp.zeros((4, 4), F32), name="scan")
    assert cost.flops == 640
    assert cost.bytes_read == 640
    assert cost.bytes_written == 320
    assert cost.peak_bytes == 576
    assert cost.by_primitive["dot_general"]["count"] == 5  # dynamic count


def test_psum_tree_comm_exact():
    """Grad-sync shape: per-leaf psum over a 4-device dp axis under
    shard_map. Per-device shards: w [2,8]=64 B, b [1]=4 B; psum moves
    2x input bytes (reduce-scatter + all-gather) -> 2*68 = 136."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) >= 4, "conftest forces an 8-device host platform"
    mesh = Mesh(np.asarray(devs[:4]), ("dp",))
    tree = {"w": jnp.zeros((8, 8), F32), "b": jnp.zeros((4,), F32)}

    def psum_tree(g):
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, "dp"), g)

    pt = shard_map(psum_tree, mesh=mesh,
                   in_specs=({"w": P("dp", None), "b": P("dp")},),
                   out_specs={"w": P(None, None), "b": P(None)},
                   check_rep=False)
    cost = jaxcost.estimate_fn(pt, tree, name="pt")
    assert cost.flops == 0
    assert cost.comm_bytes == 136
    assert cost.peak_bytes == 404


def test_liveness_releases_dead_values():
    """x[256]f32 -> t=x+1 -> u=t*2: x dies after the first eqn, so both
    eqns peak at 2048 (one live input + one output), never 3072."""
    def prog(x):
        t = x + 1.0
        return t * 2.0

    rep = peak_live_bytes(jax.make_jaxpr(prog)(jnp.zeros((256,), F32)))
    assert rep.peak_bytes == 2048


def test_cond_charges_heaviest_branch():
    """cond(v@v, v+1) on [8,8]: flops = max(1024, 64) = 1024."""
    def prog(pred, x):
        return jax.lax.cond(pred, lambda v: jnp.dot(v, v),
                            lambda v: v + 1.0, x)

    cost = jaxcost.estimate_fn(prog, jnp.asarray(True),
                               jnp.zeros((8, 8), F32), name="cond")
    assert cost.flops == 1024


# --------------------------------------------------------- donation audit
def _toy_step(params, x):
    new = {k: v - 0.1 * v for k, v in params.items()}
    return new, (x * 2).sum()


def _toy_args():
    return ({"w": jnp.zeros((16, 16), F32), "b": jnp.zeros((16,), F32)},
            jnp.zeros((8,), F32))


def test_donation_audit_flags_undonated_params():
    params, x = _toy_args()
    findings = jaxcost.audit_donation(_toy_step, params, x, name="toy")
    assert [(f.argnum, f.nbytes, f.n_leaves) for f in findings] == \
        [(0, 1088, 2)]  # w 1024 + b 64, both aval-matched to outputs
    assert not findings[0].suppressed


def test_donation_audit_clean_when_donated():
    params, x = _toy_args()
    assert jaxcost.audit_donation(_toy_step, params, x, name="toy",
                                  donate_argnums=(0,)) == []


def test_donation_audit_suppression_keeps_finding_marked():
    params, x = _toy_args()
    findings = jaxcost.audit_donation(_toy_step, params, x, name="toy",
                                      suppress={0: "kept for rollback"})
    assert len(findings) == 1
    assert findings[0].suppressed == "kept for rollback"


def _bn_step():
    """The model that motivated TrainStep's donate set: BatchNorm
    carries running-stat BUFFERS (argnum 2), updated and returned every
    step — donatable, and invisible on buffer-less models."""
    import paddle_tpu as paddle
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(16, 512),
                                 paddle.nn.BatchNorm1D(512))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    def loss_fn(m, x, y):
        d = m(x) - y
        return (d * d).mean()

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    x = paddle.to_tensor(np.zeros((4, 16), np.float32))
    y = paddle.to_tensor(np.zeros((4, 512), np.float32))
    return step, x, y


def test_trainstep_donates_buffers_the_old_set_missed():
    from paddle_tpu.analysis.jaxpr_audit import train_step_args
    step, x, y = _bn_step()
    args = train_step_args(step, x, y)
    # the pre-fix donate set (params/opt_state/rng_ctr, no buffers)
    old = jaxcost.audit_donation(step._raw_step, *args, name="bn",
                                 donate_argnums=(0, 3, 6))
    assert [(f.argnum, f.nbytes) for f in old] == [(2, 4096)]
    # the shipped set covers the running stats
    assert 2 in step._donate_argnums
    assert jaxcost.audit_donation(step._raw_step, *args, name="bn",
                                  donate_argnums=step._donate_argnums) \
        == []


def test_registry_has_zero_unsuppressed_findings():
    """ISSUE acceptance: after the TrainStep/_cache_write donation fix,
    the whole registry audits clean; the one intentional non-donation
    (serving pools, crash recovery) stays visible as suppressed."""
    findings = jaxcost.collect_donation_findings()
    unsuppressed = [f for f in findings if not f.suppressed]
    assert unsuppressed == [], "\n".join(f.format() for f in unsuppressed)
    assert any(f.program == "serving.paged_decode" and f.suppressed
               for f in findings)


def test_registry_names_cover_required_programs():
    names = set(jaxcost.registry_names())
    assert "train_step" in names
    assert {"decode.token_embed", "decode.qkv", "decode.cache_write",
            "decode.attn", "decode.head"} <= names
    assert {"serving.prefill", "serving.paged_decode"} <= names


# ------------------------------------------------ donation bitwise safety
def _twin(donate: bool):
    import paddle_tpu as paddle
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                 paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 8))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    def loss_fn(m, x, y):
        d = m(x) - y
        return (d * d).mean()

    step = paddle.jit.TrainStep(model, loss_fn, opt, donate=donate)
    return model, step


def test_donation_is_bitwise_equivalent():
    """Donation is an aliasing hint, not a numerical change: 3 steps of
    seeded twin models must match bitwise in every loss and parameter."""
    import paddle_tpu as paddle
    rng = np.random.RandomState(0)
    batches = [(rng.randn(4, 8).astype(np.float32),
                rng.randn(4, 8).astype(np.float32)) for _ in range(3)]
    runs = {}
    for donate in (True, False):
        model, step = _twin(donate)
        losses = []
        for bx, by in batches:
            out = step(paddle.to_tensor(bx), paddle.to_tensor(by))
            losses.append(np.asarray(out.numpy()
                                     if hasattr(out, "numpy") else out))
        runs[donate] = (losses,
                        [np.asarray(p._value) for p in model.parameters()])
    for ld, lu in zip(*[runs[k][0] for k in (True, False)]):
        assert np.array_equal(ld, lu)
    for pd, pu in zip(*[runs[k][1] for k in (True, False)]):
        assert np.array_equal(pd, pu)


# ------------------------------------------------------------ budget gate
def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, str(JAXCOST_CLI), *args],
        capture_output=True, text=True, cwd=str(REPO), env=env,
        timeout=600)


def test_budget_check_passes_on_committed_file():
    """ISSUE acceptance: the committed jaxcost_budget.json covers every
    registry program and the full check (costs + donation audit) is
    green."""
    p = _cli("--budget", "check", "--format", "json")
    assert p.returncode == 0, p.stdout + p.stderr
    d = json.loads(p.stdout)
    assert d["budget_violations"] == []
    assert set(d["programs"]) == set(jaxcost.registry_names())
    assert all(not f["suppressed"] or
               f["program"] == "serving.paged_decode"
               for f in d["donation_findings"])


def test_budget_check_fails_when_peak_bytes_regress(tmp_path):
    """ISSUE acceptance: shrink train_step's peak-bytes budget by 1.2x
    (i.e. the current program exceeds it by ~20% > 5% tolerance) ->
    exit 1 naming the program and metric."""
    payload = json.loads(BUDGET_FILE.read_text())
    payload["programs"]["train_step"]["peak_bytes"] = int(
        payload["programs"]["train_step"]["peak_bytes"] / 1.2)
    f = tmp_path / "budget.json"
    f.write_text(json.dumps(payload))
    p = _cli("--budget", "check", "--budget-file", str(f),
             "--programs", "train_step", "--no-donation-audit")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "BUDGET VIOLATION" in p.stdout
    assert "train_step" in p.stdout and "peak_bytes" in p.stdout


def test_budget_check_tolerates_small_drift(tmp_path):
    """A 4% overshoot sits inside the 5% tolerance -> exit 0."""
    payload = json.loads(BUDGET_FILE.read_text())
    payload["programs"]["train_step"]["peak_bytes"] = int(
        payload["programs"]["train_step"]["peak_bytes"] / 1.04)
    f = tmp_path / "budget.json"
    f.write_text(json.dumps(payload))
    p = _cli("--budget", "check", "--budget-file", str(f),
             "--programs", "train_step", "--no-donation-audit")
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_rejects_unknown_program():
    p = _cli("--programs", "no_such_program", "--no-donation-audit")
    assert p.returncode == 2
    assert "unknown program" in p.stderr


# ------------------------------------------------- hlo_bytes single source
def test_hlo_bytes_tool_is_a_thin_wrapper():
    """tools/hlo_bytes.py must carry no byte-accounting logic of its
    own — one dtype table, one parser, in analysis/hlo_bytes.py."""
    src = (REPO / "tools" / "hlo_bytes.py").read_text()
    assert "analysis.hlo_bytes" in src
    assert "def shape_bytes" not in src
    assert "def audit_text" not in src
    assert "_DTYPE_BYTES" not in src


def test_hlo_shape_bytes_and_allreduce_payload():
    assert hb.shape_bytes("f32[8,2]") == 64
    assert hb.shape_bytes("(f32[8]{0}, bf16[4,4])") == 64
    hlo = ("  %ar = f32[1024]{0} all-reduce(%p0), replica_groups={}\n"
           "  %ar2 = (f32[8]{0}, f32[16]{0}) all-reduce(%a, %b)\n"
           "  %use = f32[1024]{0} add(%ar, %ar)\n")
    assert hb.allreduce_payload(hlo) == (4096 + 32 + 64, 2)


def test_hlo_bytes_cli_runs(tmp_path):
    hlo = ("HloModule m\n\n"
           "ENTRY main {\n"
           "  %p0 = f32[8,16]{1,0} parameter(0)\n"
           "  %e = f32[8,16]{1,0} exponential(%p0)\n"
           "}\n")
    f = tmp_path / "dump.txt"
    f.write_text(hlo)
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "hlo_bytes.py"), str(f)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120)
    assert p.returncode == 0, p.stderr
    assert "exponential" in p.stdout
