"""Compile-time strategy assertions: the collectives and shardings XLA
inserts for each distributed strategy must be the expected ones.

Reference test style: the fleet meta-optimizer suite asserts on the
rewritten ProgramDesc (unittests/test_fleet_sharding_meta_optimizer.py:
`self.assertIn('c_reduce_sum', ops)` etc.). The XLA analogue here is
two-layered: sdy.sharding annotations in the LOWERED module (which state
actually got sharded) and collective ops in the COMPILED partitioned HLO.

Backend note: the CPU SPMD partitioner decomposes reduce-scatter into
all-reduce + dynamic-slice (the classic decomposition), so ZeRO
assertions accept either form; on TPU the same programs lower to native
reduce-scatter.
"""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
from paddle_tpu.parallel import (ShardedTrainStep, ShardingStage,
                                 build_mesh, set_global_mesh)


def _step(tp=1, sharding=1, dp=1, stage=ShardingStage.OFF, grad_accum=1,
          seq=16):
    mesh = build_mesh(dp=dp, pp=1, tp=tp, sp=1, sharding=sharding)
    set_global_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=seq)
    model = GPT(cfg)
    optim = opt.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh,
                            sharding_stage=stage,
                            grad_accum_steps=grad_accum)
    B = max(4, 2 * dp * sharding)
    x = paddle.to_tensor(np.zeros((B, seq), np.int64))
    y = paddle.to_tensor(np.zeros((B, seq), np.int64))
    return step, (x, y)


def _collectives(txt):
    return {
        "all-reduce": txt.count("all-reduce"),
        "reduce-scatter": txt.count("reduce-scatter"),
        "all-gather": txt.count("all-gather"),
        "collective-permute": txt.count("collective-permute"),
        "dynamic-slice": txt.count("dynamic-slice"),
    }


def _sharded_args(step, args):
    """Number of executable arguments annotated sharded over the
    'sharding' mesh axis — the analogue of counting sharded vars in the
    reference's rewritten ProgramDesc."""
    return step.lowered_text(*args).count('{"sharding"}')


def _grad_reduction_present(c):
    # native reduce-scatter (TPU) or the CPU partitioner's decomposition
    return c["reduce-scatter"] > 0 or (
        c["all-reduce"] > 0 and c["dynamic-slice"] > 0)


def test_dp_inserts_gradient_allreduce():
    """Plain dp: batch sharded over 'dp' → grads need an all-reduce
    (reference: c_allreduce_sum per grad in the rewritten program)."""
    step, args = _step(dp=8)
    c = _collectives(step.compiled_text(*args))
    assert c["all-reduce"] > 0, c


# baseline sharded-arg count: the vocab-parallel embedding contributes a
# couple of marks even with sharding off
_OFF_BASELINE = None


def _off_baseline():
    global _OFF_BASELINE
    if _OFF_BASELINE is None:
        step, args = _step(sharding=8, stage=ShardingStage.OFF)
        _OFF_BASELINE = _sharded_args(step, args)
    return _OFF_BASELINE


def test_zero1_shards_optimizer_state():
    """ZeRO-1 (OPTIMIZER): every AdamW moment tensor is sharded over the
    'sharding' axis; update runs sharded then params re-gather."""
    step, args = _step(sharding=8, stage=ShardingStage.OPTIMIZER)
    n = _sharded_args(step, args)
    assert n > _off_baseline() + 30, (n, _off_baseline())
    c = _collectives(step.compiled_text(*args))
    assert _grad_reduction_present(c), c
    assert c["all-gather"] > 0, c


def test_zero2_inserts_reduce_scatter():
    """ZeRO-2 (GRADIENT): gradient reduction lands on the owning shard
    (reference sharding meta-optimizer asserts c_reduce_sum per shard)."""
    step, args = _step(sharding=8, stage=ShardingStage.GRADIENT)
    n = _sharded_args(step, args)
    assert n > _off_baseline() + 30, (n, _off_baseline())
    c = _collectives(step.compiled_text(*args))
    assert _grad_reduction_present(c), c
    assert c["all-gather"] > 0, c  # updated shards re-gathered


def test_zero3_shards_parameters_too():
    """ZeRO-3 (PARAMETER): parameters THEMSELVES live sharded (more
    sharded executable args than ZeRO-2) and the forward all-gathers
    them on use (reference stage-3: broadcast-on-use)."""
    s2, a2 = _step(sharding=8, stage=ShardingStage.GRADIENT)
    n2 = _sharded_args(s2, a2)
    s3, a3 = _step(sharding=8, stage=ShardingStage.PARAMETER)
    n3 = _sharded_args(s3, a3)
    assert n3 > n2, (n3, n2)
    c = _collectives(s3.compiled_text(*a3))
    assert c["all-gather"] > 0, c
    assert _grad_reduction_present(c), c


def test_tp_inserts_allreduce_pair():
    """Megatron tp: column+row parallel pair → psum of the row-parallel
    output (forward) and of the column-parallel input grad (backward)
    (reference: c_allreduce in the tensor-parallel pass)."""
    step, args = _step(tp=8)
    c = _collectives(step.compiled_text(*args))
    assert c["all-reduce"] > 0, c


def test_pipeline_uses_collective_permute():
    """Pipeline parallelism: stage-to-stage activation transfer is
    ppermute (reference: send_v2/recv_v2 pairs per stage boundary)."""
    from paddle_tpu.parallel.pipeline import (PipelinedGPT,
                                              pipelined_gpt_loss_fn)
    mesh = build_mesh(dp=2, pp=4, tp=1, sp=1, sharding=1)
    set_global_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=16)
    model = PipelinedGPT(cfg, mesh)
    optim = opt.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, pipelined_gpt_loss_fn, optim, mesh=mesh)
    x = paddle.to_tensor(np.zeros((8, 16), np.int64))
    y = paddle.to_tensor(np.zeros((8, 16), np.int64))
    c = _collectives(step.compiled_text(x, y))
    assert c["collective-permute"] > 0, c
    assert c["all-reduce"] > 0, c  # dp grad sync still present


def test_large_vocab_sharded_unembed_parity():
    """Round-3 verdict weak #6: multichip evidence was tiny-geometry
    only. This runs the LARGE-vocab path — vocab 8192 split 8-way over
    'tp' (VocabParallelEmbedding masked lookup + column-parallel unembed
    with gather) at hidden 256 — and asserts 3-step loss parity against
    the unsharded single-device run."""
    import paddle_tpu.optimizer as opt2
    rng = np.random.RandomState(5)
    V, H_, T_, B_ = 8192, 256, 32, 8
    xs = [rng.randint(0, V, (B_, T_)) for _ in range(3)]
    ys = [rng.randint(0, V, (B_, T_)) for _ in range(3)]

    def run(tp):
        mesh = build_mesh(dp=1, pp=1, tp=tp, sp=1, sharding=8 // tp)
        set_global_mesh(mesh)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=V, hidden_size=H_, num_layers=2,
                        num_heads=4, max_seq_len=T_)
        model = GPT(cfg)
        optim = opt2.AdamW(1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh,
                                sharding_stage=ShardingStage.GRADIENT)
        return [float(step(paddle.to_tensor(x), paddle.to_tensor(y))
                      .numpy()) for x, y in zip(xs, ys)]

    sharded = run(tp=8)
    mesh1 = build_mesh(dp=1, pp=1, tp=1, sp=1, sharding=1,
                       devices=[__import__("jax").devices()[0]])
    set_global_mesh(mesh1)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=V, hidden_size=H_, num_layers=2,
                    num_heads=4, max_seq_len=T_)
    model = GPT(cfg)
    optim = opt2.AdamW(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh1)
    single = [float(step(paddle.to_tensor(x), paddle.to_tensor(y))
                    .numpy()) for x, y in zip(xs, ys)]
    np.testing.assert_allclose(sharded, single, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_dryrun_16_devices_full_hybrid():
    """The 16-virtual-device dryrun: pipelined dp=4/pp=2/tp=2 plus the
    full 4-way GSPMD hybrid dp=2/tp=2/sp=2/sharding=2, parity-checked
    against 1 device. Subprocess because device count is fixed at backend
    init."""
    import subprocess
    import sys
    env = dict(__import__("os").environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__; __graft_entry__.dryrun_multichip(16)"],
        cwd=__import__("os").path.dirname(__import__("os").path.dirname(
            __import__("os").path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dp=2 tp=2 sp=2 sharding=2" in out.stdout
    assert "multichip OK" in out.stdout


def test_gradient_merge_composes_with_dp():
    """gradient_merge (k micro-steps, one apply): the compiled step still
    carries the dp gradient collective, and the conditional apply is
    staged (lax.cond → HLO conditional/select)."""
    step, args = _step(dp=8, grad_accum=4)
    txt = step.compiled_text(*args)
    c = _collectives(txt)
    assert c["all-reduce"] > 0, c
    assert ("conditional" in txt) or ("select(" in txt)
