"""Mass declarative op suite driven by the paddle_tpu.testing harness.

Mirrors the reference's single-harness op verification culture
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:232 drives
~916 declarative test classes): each entry below is one op case — forward vs
a numpy/torch oracle, eager tape grads vs float64 central finite differences.

The closing audit test asserts every registered op is exercised here or is on
the explicit exemption list (ops exercised by other test files — the
reference's white_list/ pattern).
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.testing import OpTestCase, run_case

rng = np.random.RandomState(7)


def r(*shape, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


def rpos(*shape, lo=0.3, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


def t_ref(fn):
    """Build a numpy oracle from a torch function."""
    def oracle(*args, **kw):
        targs = []
        for a in args:
            if isinstance(a, np.ndarray):
                if np.issubdtype(a.dtype, np.floating):
                    a = torch.tensor(a.astype(np.float64))
                else:
                    a = torch.tensor(a)
            targs.append(a)
        out = fn(*targs, **kw)
        if isinstance(out, (tuple, list)):
            return [o.numpy() if torch.is_tensor(o) else o for o in out]
        return out.numpy()
    return oracle


C = OpTestCase

# -- unary elementwise -------------------------------------------------------
UNARY = [
    C(paddle.abs, (r(2, 3),), ref=np.abs, grad=(0,), op_types=["abs"]),
    C(paddle.acos, (r(2, 3, lo=-.9, hi=.9),), ref=np.arccos, grad=(0,), op_types=["acos"]),
    C(paddle.acosh, (rpos(2, 3, lo=1.2, hi=3),), ref=np.arccosh, grad=(0,), op_types=["acosh"]),
    C(paddle.asin, (r(2, 3, lo=-.9, hi=.9),), ref=np.arcsin, grad=(0,), op_types=["asin"]),
    C(paddle.asinh, (r(2, 3),), ref=np.arcsinh, grad=(0,), op_types=["asinh"]),
    C(paddle.atan, (r(2, 3),), ref=np.arctan, grad=(0,), op_types=["atan"]),
    C(paddle.atanh, (r(2, 3, lo=-.9, hi=.9),), ref=np.arctanh, grad=(0,), op_types=["atanh"]),
    C(paddle.ceil, (r(2, 3),), ref=np.ceil, op_types=["ceil"]),
    C(paddle.cos, (r(2, 3),), ref=np.cos, grad=(0,), op_types=["cos"]),
    C(paddle.cosh, (r(2, 3),), ref=np.cosh, grad=(0,), op_types=["cosh"]),
    C(paddle.digamma, (rpos(2, 3),), ref=t_ref(torch.digamma), grad=(0,), op_types=["digamma"]),
    C(paddle.erf, (r(2, 3),), ref=t_ref(torch.erf), grad=(0,), op_types=["erf"]),
    C(paddle.erfinv, (r(2, 3, lo=-.8, hi=.8),), ref=t_ref(torch.erfinv), grad=(0,), op_types=["erfinv"]),
    C(paddle.exp, (r(2, 3),), ref=np.exp, grad=(0,), op_types=["exp"]),
    C(paddle.expm1, (r(2, 3),), ref=np.expm1, grad=(0,), op_types=["expm1"]),
    C(paddle.floor, (r(2, 3),), ref=np.floor, op_types=["floor"]),
    C(paddle.frac, (r(2, 3),), ref=t_ref(torch.frac), op_types=["frac"]),
    C(paddle.i0, (r(2, 3),), ref=t_ref(torch.i0), op_types=["i0"]),
    C(paddle.i0e, (r(2, 3),), ref=t_ref(torch.special.i0e), op_types=["i0e"]),
    C(paddle.i1, (r(2, 3),), ref=t_ref(torch.special.i1), op_types=["i1"]),
    C(paddle.i1e, (r(2, 3),), ref=t_ref(torch.special.i1e), op_types=["i1e"]),
    C(paddle.lgamma, (rpos(2, 3),), ref=t_ref(torch.lgamma), grad=(0,), op_types=["lgamma"]),
    C(paddle.log, (rpos(2, 3),), ref=np.log, grad=(0,), op_types=["log"]),
    C(paddle.log10, (rpos(2, 3),), ref=np.log10, grad=(0,), op_types=["log10"]),
    C(paddle.log1p, (rpos(2, 3),), ref=np.log1p, grad=(0,), op_types=["log1p"]),
    C(paddle.log2, (rpos(2, 3),), ref=np.log2, grad=(0,), op_types=["log2"]),
    C(paddle.neg, (r(2, 3),), ref=lambda x: -x, grad=(0,), op_types=["neg"]),
    C(paddle.reciprocal, (rpos(2, 3),), ref=lambda x: 1 / x, grad=(0,), op_types=["reciprocal"]),
    C(paddle.rint, (r(2, 3),), ref=np.rint, op_types=["rint"]),
    C(paddle.round, (r(2, 3),), ref=np.rint, op_types=["round"]),
    C(paddle.rsqrt, (rpos(2, 3),), ref=lambda x: 1 / np.sqrt(x), grad=(0,), op_types=["rsqrt"]),
    C(F.sigmoid, (r(2, 3),), ref=t_ref(torch.sigmoid), grad=(0,), op_types=["sigmoid"]),
    C(paddle.sign, (r(2, 3),), ref=np.sign, op_types=["sign"]),
    C(paddle.sin, (r(2, 3),), ref=np.sin, grad=(0,), op_types=["sin"]),
    C(paddle.sinh, (r(2, 3),), ref=np.sinh, grad=(0,), op_types=["sinh"]),
    C(paddle.sqrt, (rpos(2, 3),), ref=np.sqrt, grad=(0,), op_types=["sqrt"]),
    C(paddle.square, (r(2, 3),), ref=np.square, grad=(0,), op_types=["square"]),
    C(paddle.tan, (r(2, 3, lo=-1, hi=1),), ref=np.tan, grad=(0,), op_types=["tan"]),
    C(paddle.tanh, (r(2, 3),), ref=np.tanh, grad=(0,), op_types=["tanh"]),
    C(paddle.trunc, (r(2, 3),), ref=np.trunc, op_types=["trunc"]),
    C(paddle.deg2rad, (r(2, 3, lo=-180, hi=180),), ref=np.deg2rad, grad=(0,), op_types=["deg2rad"]),
    C(paddle.rad2deg, (r(2, 3),), ref=np.rad2deg, grad=(0,), op_types=["rad2deg"]),
    C(paddle.angle, (r(2, 3),), ref=t_ref(torch.angle), op_types=["angle"]),
    C(paddle.conj, (r(2, 3),), ref=np.conj, op_types=["conj"]),
]

# -- binary elementwise ------------------------------------------------------
BINARY = [
    C(paddle.add, (r(2, 3), r(2, 3)), ref=np.add, grad=(0, 1), op_types=["elementwise_add"]),
    C(paddle.subtract, (r(2, 3), r(3)), ref=np.subtract, grad=(0, 1), op_types=["elementwise_sub"]),
    C(paddle.multiply, (r(2, 3), r(2, 1)), ref=np.multiply, grad=(0, 1), op_types=["elementwise_mul"]),
    C(paddle.divide, (r(2, 3), rpos(2, 3)), ref=np.true_divide, grad=(0, 1), op_types=["elementwise_div"]),
    C(paddle.floor_divide, (rpos(2, 3, hi=9), rpos(2, 3)), ref=np.floor_divide, op_types=["elementwise_floordiv"]),
    C(paddle.remainder, (rpos(2, 3, hi=9), rpos(2, 3)), ref=np.remainder, op_types=["elementwise_mod"]),
    C(paddle.pow, (rpos(2, 3), 2.0), ref=lambda x, y: np.power(x, y), grad=(0,), op_types=["elementwise_pow"]),
    C(paddle.maximum, (r(2, 3), r(2, 3)), ref=np.maximum, grad=(0, 1), op_types=["elementwise_max"]),
    C(paddle.minimum, (r(2, 3), r(2, 3)), ref=np.minimum, grad=(0, 1), op_types=["elementwise_min"]),
    C(paddle.fmax, (r(2, 3), r(2, 3)), ref=np.fmax, op_types=["elementwise_fmax"]),
    C(paddle.fmin, (r(2, 3), r(2, 3)), ref=np.fmin, op_types=["elementwise_fmin"]),
    C(paddle.atan2, (r(2, 3), rpos(2, 3)), ref=np.arctan2, grad=(0, 1), op_types=["atan2"]),
    C(paddle.hypot, (r(2, 3), r(2, 3)), ref=np.hypot, op_types=["hypot"]),
    C(paddle.logaddexp, (r(2, 3), r(2, 3)), ref=np.logaddexp, grad=(0, 1), op_types=["logaddexp"]),
    C(paddle.nextafter, (r(2, 3), r(2, 3)), ref=np.nextafter, op_types=["nextafter"], atol=0, rtol=1e-6),
    C(paddle.copysign, (r(2, 3), r(2, 3)), ref=np.copysign, op_types=["copysign"]),
    C(paddle.heaviside, (r(2, 3), r(2, 3)), ref=np.heaviside, op_types=["elementwise_heaviside"]),
    C(paddle.gcd, (np.array([12, 20, 7]), np.array([8, 5, 14])), ref=np.gcd, op_types=["gcd"]),
    C(paddle.lcm, (np.array([4, 6, 7]), np.array([6, 8, 14])), ref=np.lcm, op_types=["lcm"]),
    C(paddle.inner, (r(2, 4), r(3, 4)), ref=np.inner, grad=(0, 1), op_types=["inner"]),
    C(paddle.outer, (r(3), r(4)), ref=np.outer, grad=(0, 1), op_types=["outer"]),
    C(paddle.kron, (r(2, 2), r(2, 3)), ref=np.kron, grad=(0, 1), op_types=["kron"]),
    C(paddle.divide_no_nan, (r(2, 3), np.array([[1., 0., 2.], [0., 1., 1.]], np.float32)),
      ref=lambda x, y: np.where(y == 0, 0.0, x / np.where(y == 0, 1, y)),
      op_types=["divide_no_nan"]),
]

# -- reductions / cumulative -------------------------------------------------
REDUCE = [
    C(paddle.sum, (r(2, 3, 4),), {"axis": 1}, ref=lambda x, axis: x.sum(axis),
      grad=(0,), op_types=["reduce_sum"]),
    C(paddle.mean, (r(2, 3, 4),), {"axis": [0, 2]}, ref=lambda x, axis: x.mean((0, 2)),
      grad=(0,), op_types=["reduce_mean"]),
    C(paddle.max, (r(2, 5),), {"axis": 1}, ref=lambda x, axis: x.max(axis),
      grad=(0,), op_types=["reduce_max"]),
    C(paddle.min, (r(2, 5),), {"axis": -1, "keepdim": True},
      ref=lambda x, axis, keepdim: x.min(axis, keepdims=True), grad=(0,), op_types=["reduce_min"]),
    C(paddle.prod, (rpos(2, 3),), {"axis": 0}, ref=lambda x, axis: x.prod(0),
      grad=(0,), op_types=["reduce_prod"]),
    C(paddle.amax, (r(2, 5),), {"axis": 1}, ref=lambda x, axis: x.max(1), op_types=["reduce_amax"]),
    C(paddle.amin, (r(2, 5),), {"axis": 1}, ref=lambda x, axis: x.min(1), op_types=["reduce_amin"]),
    C(paddle.nansum, (np.array([[1., np.nan, 2.], [3., 4., np.nan]], np.float32),),
      ref=np.nansum, op_types=["reduce_nansum"]),
    C(paddle.nanmean, (np.array([[1., np.nan, 2.], [3., 4., np.nan]], np.float32),),
      ref=np.nanmean, op_types=["reduce_nanmean"]),
    C(paddle.all, (np.array([[True, False], [True, True]]),), {"axis": 1},
      ref=lambda x, axis: x.all(1), op_types=["all"]),
    C(paddle.any, (np.array([[True, False], [False, False]]),), {"axis": 1},
      ref=lambda x, axis: x.any(1), op_types=["any"]),
    C(paddle.logsumexp, (r(3, 4),), {"axis": 1},
      ref=lambda x, axis: np.log(np.exp(x).sum(1)), grad=(0,), op_types=["logsumexp"]),
    C(paddle.count_nonzero, (np.array([[0., 1.], [2., 0.]], np.float32),),
      ref=lambda x: np.count_nonzero(x), op_types=[]),
    C(paddle.std, (r(3, 4),), {"axis": 1},
      ref=lambda x, axis: x.astype(np.float64).std(1, ddof=1), grad=(0,), op_types=["std"]),
    C(paddle.var, (r(3, 4),), {"axis": 1},
      ref=lambda x, axis: x.astype(np.float64).var(1, ddof=1), grad=(0,), op_types=["var"]),
    C(paddle.median, (r(3, 5),), {"axis": 1},
      ref=lambda x, axis: np.median(x, 1), op_types=["median"]),
    C(paddle.quantile, (r(3, 5),), {"q": 0.5, "axis": 1},
      ref=lambda x, q, axis: np.quantile(x.astype(np.float64), q, axis=1), op_types=["quantile"]),
    C(paddle.cumsum, (r(3, 4),), {"axis": 1}, ref=lambda x, axis: np.cumsum(x, 1),
      grad=(0,), op_types=["cumsum"]),
    C(paddle.cumprod, (rpos(3, 4),), {"dim": 1}, ref=lambda x, dim: np.cumprod(x, 1),
      grad=(0,), op_types=["cumprod"]),
    C(paddle.cummax, (r(3, 4),), {"axis": 1},
      ref=lambda x, axis: [np.maximum.accumulate(x, 1), None], op_types=["cummax"]),
    C(paddle.logcumsumexp, (r(3, 4),), {"axis": 1},
      ref=lambda x, axis: np.log(np.cumsum(np.exp(x.astype(np.float64)), 1)),
      op_types=["logcumsumexp"]),
]

# -- linalg ------------------------------------------------------------------
def _spd(n):
    a = rng.randn(n, n).astype("float32")
    return a @ a.T + n * np.eye(n, dtype="float32")


LINALG = [
    C(paddle.matmul, (r(2, 3, 4), r(2, 4, 5)), ref=np.matmul, grad=(0, 1),
      op_types=["matmul_v2"]),
    C(paddle.bmm, (r(2, 3, 4), r(2, 4, 5)), ref=np.matmul, op_types=["bmm"]),
    C(paddle.mv, (r(3, 4), r(4)), ref=np.matmul, grad=(0, 1), op_types=["mv"]),
    C(paddle.dot, (r(4), r(4)), ref=np.dot, grad=(0, 1), op_types=["dot"]),
    C(paddle.addmm, (r(2, 3), r(2, 4), r(4, 3)),
      ref=lambda i, x, y: i + x @ y, grad=(0, 1, 2), op_types=["addmm"]),
    C(paddle.linalg.multi_dot, ([r(2, 3), r(3, 4), r(4, 2)],),
      ref=lambda xs: xs[0] @ xs[1] @ xs[2], op_types=["multi_dot"]),
    C(paddle.tensordot, (r(2, 3, 4), r(4, 3, 2)), {"axes": 1},
      ref=lambda x, y, axes: np.tensordot(x, y, 1), op_types=["tensordot"]),
    C(paddle.einsum, ("ij,jk->ik", r(2, 3), r(3, 4)),
      ref=lambda s, a, b: np.einsum(s, a, b), op_types=["einsum"]),
    C(paddle.trace, (r(4, 4),), ref=np.trace, grad=(0,), op_types=["trace"]),
    C(paddle.diagonal, (r(3, 4),), ref=lambda x: np.diagonal(x), op_types=["diagonal"]),
    C(paddle.det, (_spd(3),), ref=np.linalg.det, rtol=1e-3, op_types=["det"]),
    C(paddle.linalg.slogdet, (_spd(3),),
      ref=lambda x: np.array(np.linalg.slogdet(x.astype(np.float64))),
      rtol=1e-3, op_types=["slogdet"]),
    C(paddle.inverse, (_spd(3),), ref=np.linalg.inv, rtol=1e-3, op_types=["inverse"]),
    C(paddle.cholesky, (_spd(3),), ref=np.linalg.cholesky, rtol=1e-3, op_types=["cholesky"]),
    C(paddle.linalg.solve, (_spd(3), r(3, 2)),
      ref=lambda a, b: np.linalg.solve(a.astype(np.float64), b), rtol=1e-3,
      op_types=["solve"]),
    C(paddle.linalg.triangular_solve,
      (np.tril(_spd(3)), r(3, 2)), {"upper": False},
      ref=lambda a, b, upper: np.linalg.solve(a.astype(np.float64), b),
      rtol=1e-3, op_types=["triangular_solve"]),
    C(paddle.linalg.cholesky_solve, (r(3, 1), np.linalg.cholesky(_spd(3)).astype("float32")),
      {"upper": False}, op_types=["cholesky_solve"]),
    C(paddle.linalg.matrix_power, (_spd(3), 2),
      ref=lambda x, n: np.linalg.matrix_power(x.astype(np.float64), n),
      rtol=1e-3, op_types=["matrix_power"]),
    C(paddle.linalg.pinv, (r(4, 3),),
      ref=lambda x: np.linalg.pinv(x.astype(np.float64)), rtol=1e-2, atol=1e-4,
      op_types=["pinv"]),
    C(paddle.linalg.matrix_rank, (_spd(3),), ref=lambda x: 3, op_types=["matrix_rank"]),
    C(paddle.linalg.qr, (r(4, 3),), op_types=["qr"]),
    C(paddle.linalg.svd, (r(4, 3),), op_types=["svd"]),
    C(paddle.linalg.eigh, (_spd(3),), op_types=["eigh"]),
    C(paddle.linalg.eig, (_spd(3),), op_types=["eig"]),
    C(paddle.linalg.norm, (r(3, 4),), ref=lambda x: np.linalg.norm(x),
      op_types=["frobenius_norm", "p_norm"]),
    C(paddle.cross, (r(3, 3), r(3, 3)), {"axis": 1},
      ref=lambda x, y, axis: np.cross(x, y, axis=1), grad=(0, 1), op_types=["cross"]),
    C(paddle.linalg.cov, (r(3, 6),), ref=lambda x: np.cov(x.astype(np.float64)),
      rtol=1e-3, op_types=["cov"]),
    C(paddle.corrcoef, (r(3, 6),), ref=lambda x: np.corrcoef(x.astype(np.float64)),
      rtol=1e-3, op_types=["corrcoef"]),
]

# -- manipulation ------------------------------------------------------------
x234 = np.arange(24, dtype="float32").reshape(2, 3, 4)

MANIP = [
    C(paddle.reshape, (x234, [4, 6]), ref=lambda x, s: x.reshape(4, 6),
      grad=(0,), op_types=["reshape"]),
    C(paddle.transpose, (x234, [2, 0, 1]),
      ref=lambda x, p: x.transpose(2, 0, 1), grad=(0,), op_types=["transpose"]),
    C(paddle.flatten, (x234,), {"start_axis": 1, "stop_axis": 2},
      ref=lambda x, start_axis, stop_axis: x.reshape(2, 12), op_types=["flatten"]),
    C(paddle.squeeze, (np.ones((1, 2, 1, 3), np.float32),), {"axis": 0},
      ref=lambda x, axis: x.squeeze(0), op_types=["squeeze"]),
    C(paddle.unsqueeze, (x234, [0, -1]),
      ref=lambda x, ax: x[None, ..., None], op_types=["unsqueeze"]),
    C(paddle.concat, ([r(2, 3), r(2, 3)],), {"axis": 1},
      ref=lambda xs, axis: np.concatenate(xs, 1), op_types=["concat"]),
    C(paddle.stack, ([r(2, 3), r(2, 3)],), {"axis": 0},
      ref=lambda xs, axis: np.stack(xs, 0), op_types=["stack"]),
    C(paddle.manipulation.unstack, (r(3, 2),), {"axis": 0},
      ref=lambda x, axis: list(x), op_types=["unstack"]),
    C(paddle.split, (x234, [1, 2]), {"axis": 1},
      ref=lambda x, s, axis: [x[:, :1], x[:, 1:]], op_types=["split"]),
    C(paddle.tile, (r(2, 3), [2, 1]), ref=lambda x, reps: np.tile(x, (2, 1)),
      grad=(0,), op_types=["tile"]),
    C(paddle.expand, (r(1, 3), [4, 3]),
      ref=lambda x, s: np.broadcast_to(x, (4, 3)), grad=(0,), op_types=["expand"]),
    C(paddle.flip, (x234, [0, 2]), ref=lambda x, ax: x[::-1, :, ::-1],
      op_types=["flip"]),
    C(paddle.roll, (x234, 2), {"axis": 1}, ref=lambda x, s, axis: np.roll(x, 2, 1),
      op_types=["roll"]),
    C(paddle.manipulation.rot90, (r(3, 3),), ref=lambda x: np.rot90(x),
      op_types=["rot90"]),
    C(paddle.moveaxis, (x234, 0, 2), ref=lambda x, a, b: np.moveaxis(x, 0, 2),
      op_types=["moveaxis"]),
    C(paddle.repeat_interleave, (r(2, 3), 2), {"axis": 1},
      ref=lambda x, n, axis: np.repeat(x, 2, 1), op_types=["repeat_interleave"]),
    C(paddle.gather, (r(4, 3), np.array([0, 2])),
      ref=lambda x, i: x[i], grad=(0,), op_types=["gather"]),
    C(paddle.gather_nd, (r(4, 3), np.array([[0, 1], [2, 2]])),
      ref=lambda x, i: x[[0, 2], [1, 2]], grad=(0,), op_types=["gather_nd"]),
    C(paddle.scatter, (r(4, 3), np.array([0, 2]), np.ones((2, 3), np.float32)),
      ref=lambda x, i, u: np.concatenate([u[:1], x[1:2], u[1:], x[3:]]),
      grad=(0, 2), op_types=["scatter"]),
    C(paddle.scatter_nd_add,
      (np.zeros((4,), np.float32), np.array([[1], [2], [1]]),
       np.array([1., 2., 3.], np.float32)),
      ref=lambda x, i, u: np.array([0., 4., 2., 0.], np.float32),
      op_types=["scatter_nd_add"]),
    C(paddle.index_select, (r(4, 3), np.array([0, 2])), {"axis": 0},
      ref=lambda x, i, axis: x[[0, 2]], grad=(0,), op_types=["index_select"]),
    C(paddle.index_add, (r(4, 3), np.array([0, 2]), 0, np.ones((2, 3), np.float32)),
      op_types=["index_add"]),
    C(paddle.index_sample, (r(3, 5), np.array([[0, 1], [2, 3], [4, 0]])),
      ref=lambda x, i: np.take_along_axis(x, i, 1), op_types=["index_sample"]),
    C(paddle.manipulation.put_along_axis,
      (r(3, 5), np.array([[0], [1], [2]]), np.zeros((3, 1), np.float32), 1),
      op_types=["put_along_axis"]),
    C(paddle.manipulation.take_along_axis, (r(3, 5), np.array([[0], [1], [2]]), 1),
      ref=lambda x, i, axis: np.take_along_axis(x, i, 1), grad=(0,),
      op_types=["take_along_axis"]),
    C(paddle.masked_select, (r(2, 3), np.array([[True, False, True],
                                                [False, True, False]])),
      ref=lambda x, m: x[m], op_types=["masked_select"]),
    C(paddle.manipulation.masked_fill,
      (r(2, 3), np.array([[True, False, True], [False, True, False]]), 0.0),
      ref=lambda x, m, v: np.where(m, 0.0, x), grad=(0,), op_types=["masked_fill"]),
    C(paddle.where, (np.array([[True, False], [False, True]]), r(2, 2), r(2, 2)),
      ref=np.where, grad=(1, 2), op_types=["where"]),
    C(paddle.diag, (r(4),), ref=np.diag, op_types=["diag"]),
    C(paddle.diagflat, (r(2, 2),), ref=lambda x: np.diagflat(x), op_types=["diagflat"]),
    C(paddle.tril, (r(3, 3),), ref=np.tril, grad=(0,), op_types=["tril"]),
    C(paddle.triu, (r(3, 3),), ref=np.triu, op_types=["triu"]),
    C(F.one_hot, (np.array([0, 2, 1]), 4),
      ref=lambda x, n: np.eye(4, dtype="float32")[x], op_types=["one_hot_v2"]),
    C(paddle.as_complex, (r(2, 3, 2),),
      ref=lambda x: x[..., 0] + 1j * x[..., 1], op_types=["as_complex"]),
    C(paddle.as_real, (r(2, 3).astype(np.complex64),),
      ref=lambda x: np.stack([x.real, x.imag], -1), op_types=["as_real"]),
    C(paddle.real, ((r(2, 2) + 1j * r(2, 2)).astype(np.complex64),),
      ref=np.real, op_types=["real"]),
    C(paddle.imag, ((r(2, 2) + 1j * r(2, 2)).astype(np.complex64),),
      ref=np.imag, op_types=["imag"]),
    C(paddle.ones_like, (r(2, 3),), ref=np.ones_like, op_types=["ones_like"]),
    C(paddle.zeros_like, (r(2, 3),), ref=np.zeros_like, op_types=["zeros_like"]),
    C(paddle.assign, (r(2, 3),), ref=lambda x: x, op_types=["assign"]),
    C(paddle.cast, (r(2, 3), "int32"),
      ref=lambda x, d: x.astype(np.int32), op_types=["cast"]),
]

# -- search / sort -----------------------------------------------------------
SEARCH = [
    C(paddle.argmax, (r(3, 5),), {"axis": 1}, ref=lambda x, axis: x.argmax(1),
      op_types=["arg_max"]),
    C(paddle.argmin, (r(3, 5),), {"axis": 1}, ref=lambda x, axis: x.argmin(1),
      op_types=["arg_min"]),
    C(paddle.argsort, (r(3, 5),), {"axis": 1},
      ref=lambda x, axis: np.argsort(x, 1, kind="stable"), op_types=["argsort"]),
    C(paddle.sort, (r(3, 5),), {"axis": 1}, ref=lambda x, axis: np.sort(x, 1),
      grad=(0,), op_types=["sort"]),
    C(paddle.topk, (r(3, 5), 2), {"axis": 1},
      ref=lambda x, k, axis: [np.sort(x, 1)[:, ::-1][:, :2], None],
      grad=(0,), op_types=["top_k_v2"]),
    C(paddle.kthvalue, (r(3, 5), 2), {"axis": 1},
      ref=lambda x, k, axis: [np.sort(x, 1)[:, 1], None], op_types=["kthvalue"]),
    C(paddle.mode, (np.array([[1., 1., 2.], [3., 3., 3.]], np.float32),),
      ref=lambda x: [np.array([1., 3.], np.float32), None], op_types=["mode"]),
    C(paddle.searchsorted, (np.array([1., 3., 5., 7.], np.float32),
                            np.array([2., 6.], np.float32)),
      ref=lambda s, v: np.searchsorted(s, v), op_types=["searchsorted"]),
    C(paddle.bucketize, (np.array([2., 6.], np.float32),
                         np.array([1., 3., 5., 7.], np.float32)),
      ref=lambda v, s: np.searchsorted(s, v), op_types=["bucketize"]),
    C(paddle.histogram, (r(20),), {"bins": 5, "min": -2, "max": 2},
      ref=lambda x, bins, min, max: np.histogram(x, 5, (-2, 2))[0],
      op_types=["histogram"]),
    C(paddle.bincount, (np.array([0, 1, 1, 3]),),
      ref=lambda x: np.bincount(x), op_types=["bincount"]),
]

# -- logic / comparison ------------------------------------------------------
LOGIC = [
    C(paddle.equal, (np.array([1, 2]), np.array([1, 3])),
      ref=np.equal, op_types=["equal"]),
    C(paddle.not_equal, (np.array([1, 2]), np.array([1, 3])),
      ref=np.not_equal, op_types=["not_equal"]),
    C(paddle.greater_than, (r(2, 2), r(2, 2)), ref=np.greater,
      op_types=["greater_than"]),
    C(paddle.greater_equal, (r(2, 2), r(2, 2)), ref=np.greater_equal,
      op_types=["greater_equal"]),
    C(paddle.less_than, (r(2, 2), r(2, 2)), ref=np.less, op_types=["less_than"]),
    C(paddle.less_equal, (r(2, 2), r(2, 2)), ref=np.less_equal,
      op_types=["less_equal"]),
    C(paddle.logical_and, (np.array([True, False]), np.array([True, True])),
      ref=np.logical_and, op_types=["logical_and"]),
    C(paddle.logical_or, (np.array([True, False]), np.array([False, False])),
      ref=np.logical_or, op_types=["logical_or"]),
    C(paddle.logical_xor, (np.array([True, False]), np.array([True, True])),
      ref=np.logical_xor, op_types=["logical_xor"]),
    C(paddle.logical_not, (np.array([True, False]),), ref=np.logical_not,
      op_types=["logical_not"]),
    C(paddle.bitwise_and, (np.array([5, 3]), np.array([3, 1])),
      ref=np.bitwise_and, op_types=["bitwise_and"]),
    C(paddle.bitwise_or, (np.array([5, 3]), np.array([3, 1])),
      ref=np.bitwise_or, op_types=["bitwise_or"]),
    C(paddle.bitwise_xor, (np.array([5, 3]), np.array([3, 1])),
      ref=np.bitwise_xor, op_types=["bitwise_xor"]),
    C(paddle.bitwise_not, (np.array([5, 3]),), ref=np.bitwise_not,
      op_types=["bitwise_not"]),
    C(paddle.isnan, (np.array([1., np.nan], np.float32),), ref=np.isnan,
      op_types=["isnan"]),
    C(paddle.isinf, (np.array([1., np.inf], np.float32),), ref=np.isinf,
      op_types=["isinf"]),
    C(paddle.isfinite, (np.array([1., np.inf], np.float32),), ref=np.isfinite,
      op_types=["isfinite"]),
]

# -- activations -------------------------------------------------------------
ACT = [
    C(F.relu, (r(2, 3),), ref=lambda x: np.maximum(x, 0), grad=(0,), op_types=["relu"]),
    C(F.relu6, (r(2, 3, lo=-1, hi=8),), ref=lambda x: np.clip(x, 0, 6), op_types=["relu6"]),
    C(F.elu, (r(2, 3),), ref=t_ref(tF.elu), grad=(0,), op_types=["elu"]),
    C(F.selu, (r(2, 3),), ref=t_ref(tF.selu), op_types=["selu"]),
    C(F.celu, (r(2, 3),), ref=t_ref(tF.celu), op_types=["celu"]),
    C(F.gelu, (r(2, 3),), ref=t_ref(tF.gelu), grad=(0,), op_types=["gelu"]),
    C(F.silu, (r(2, 3),), ref=t_ref(tF.silu), grad=(0,), op_types=["silu"]),
    C(F.mish, (r(2, 3),), ref=t_ref(tF.mish), op_types=["mish"]),
    C(F.softplus, (r(2, 3),), ref=t_ref(tF.softplus), grad=(0,), op_types=["softplus"]),
    C(F.softshrink, (r(2, 3),), ref=t_ref(tF.softshrink), op_types=["softshrink"]),
    C(F.softsign, (r(2, 3),), ref=t_ref(tF.softsign), op_types=["softsign"]),
    C(F.hardtanh, (r(2, 3),), ref=t_ref(tF.hardtanh), op_types=["hard_tanh"]),
    C(F.hardshrink, (r(2, 3),), ref=t_ref(tF.hardshrink), op_types=["hard_shrink"]),
    C(F.hardsigmoid, (r(2, 3, lo=-6, hi=6),), op_types=["hard_sigmoid"]),
    C(F.hardswish, (r(2, 3, lo=-6, hi=6),), ref=t_ref(tF.hardswish),
      op_types=["hard_swish"]),
    C(F.leaky_relu, (r(2, 3),), {"negative_slope": 0.1},
      ref=lambda x, negative_slope: np.where(x > 0, x, 0.1 * x), grad=(0,),
      op_types=["leaky_relu"]),
    C(F.prelu, (r(2, 3), np.array([0.25], np.float32)),
      ref=lambda x, w: np.where(x > 0, x, 0.25 * x), op_types=["prelu"]),
    C(F.log_sigmoid, (r(2, 3),), ref=t_ref(tF.logsigmoid), grad=(0,),
      op_types=["logsigmoid"]),
    C(F.log_softmax, (r(2, 5),), {"axis": -1}, ref=t_ref(lambda x, axis: tF.log_softmax(x, -1)),
      grad=(0,), op_types=["log_softmax"]),
    C(F.softmax, (r(2, 5),), {"axis": -1}, ref=t_ref(lambda x, axis: tF.softmax(x, -1)),
      grad=(0,), op_types=["softmax"]),
    C(F.tanhshrink, (r(2, 3),), ref=t_ref(tF.tanhshrink), op_types=["tanh_shrink"]),
    C(F.thresholded_relu, (r(2, 3),),
      ref=lambda x: np.where(x > 1.0, x, 0.0), op_types=["thresholded_relu"]),
    C(F.swish, (r(2, 3),), ref=t_ref(tF.silu), op_types=[]),
    C(paddle.stanh, (r(2, 3),),
      ref=lambda x: 1.7159 * np.tanh(0.67 * x), op_types=["stanh"]),
    C(F.maxout, (r(2, 4, 3, 3), 2), op_types=["maxout"]),
    C(F.glu, (r(2, 4),), ref=t_ref(lambda x: tF.glu(x, -1)), op_types=["glu"]),
    C(F.gumbel_softmax, (r(2, 5),), op_types=["gumbel_softmax"]),
]

# -- losses / misc nn --------------------------------------------------------
_logits = r(4, 5)
_labels = np.array([1, 0, 4, 2])

LOSS = [
    C(F.mse_loss, (r(3, 4), r(3, 4)), ref=t_ref(tF.mse_loss), grad=(0,),
      op_types=["mse_loss"]),
    C(F.l1_loss, (r(3, 4), r(3, 4)), ref=t_ref(tF.l1_loss), op_types=["l1_loss"]),
    C(F.binary_cross_entropy, (rpos(3, 4, lo=0.1, hi=0.9), rpos(3, 4, lo=0.1, hi=0.9)),
      ref=t_ref(tF.binary_cross_entropy), grad=(0,), op_types=["bce_loss"]),
    C(F.binary_cross_entropy_with_logits, (r(3, 4), rpos(3, 4, lo=0, hi=1)),
      ref=t_ref(tF.binary_cross_entropy_with_logits), grad=(0,),
      op_types=["bce_with_logits"]),
    C(F.cross_entropy, (_logits, _labels),
      ref=t_ref(lambda x, y: tF.cross_entropy(x, torch.tensor(np.asarray(y)))),
      grad=(0,), op_types=["softmax_with_cross_entropy",
                           "softmax_with_cross_entropy_keepdim"]),
    C(F.nll_loss, (np.log(tF.softmax(torch.tensor(_logits), -1).numpy() + 1e-9), _labels),
      ref=t_ref(lambda x, y: tF.nll_loss(x, torch.tensor(np.asarray(y)))),
      op_types=["nll_loss"]),
    C(F.kl_div, (np.log(rpos(3, 4, lo=.1, hi=.9)), rpos(3, 4, lo=.1, hi=.9)),
      ref=t_ref(lambda x, y: tF.kl_div(x, y)), op_types=["kl_div"]),
    C(F.smooth_l1_loss, (r(3, 4), r(3, 4)), ref=t_ref(tF.smooth_l1_loss),
      op_types=["smooth_l1_loss", "huber_loss"]),
    C(F.margin_ranking_loss, (r(3), r(3), np.sign(r(3)).astype("float32")),
      ref=t_ref(tF.margin_ranking_loss), op_types=["margin_ranking_loss"]),
    C(F.hinge_embedding_loss, (r(3, 4), np.sign(r(3, 4)).astype("float32")),
      ref=t_ref(tF.hinge_embedding_loss), op_types=["hinge_embedding_loss"]),
    C(F.cosine_embedding_loss, (r(3, 4), r(3, 4), np.sign(r(3)).astype("float32")),
      ref=t_ref(tF.cosine_embedding_loss), op_types=["cosine_embedding_loss"]),
    C(F.triplet_margin_loss, (r(3, 4), r(3, 4), r(3, 4)),
      ref=t_ref(tF.triplet_margin_loss), op_types=["triplet_margin_loss"]),
    C(F.log_loss, (rpos(3, 1, lo=.1, hi=.9), rpos(3, 1, lo=0, hi=1)),
      op_types=["log_loss"]),
    C(F.label_smooth, (np.eye(4, dtype="float32"),),
      ref=lambda x: x * 0.9 + 0.1 / 4, op_types=["label_smooth"]),
    C(F.sigmoid_cross_entropy_with_logits, (r(3, 4), rpos(3, 4, lo=0, hi=1)),
      ref=t_ref(lambda x, y: tF.binary_cross_entropy_with_logits(
          x, y, reduction="none")), op_types=["sigmoid_cross_entropy_with_logits"]),
    C(F.square_error_cost, (r(3), r(3)), ref=lambda x, y: (x - y) ** 2, op_types=[]),
    C(F.cosine_similarity, (r(3, 4), r(3, 4)),
      ref=t_ref(lambda a, b: tF.cosine_similarity(a, b)),
      op_types=["cosine_similarity"]),
    C(F.normalize, (r(3, 4),), ref=t_ref(lambda x: tF.normalize(x)),
      op_types=["normalize_l2"]),
    C(F.linear, (r(3, 4), r(4, 5), r(5)),
      ref=lambda x, w, b: x @ w + b, grad=(0, 1, 2), op_types=["linear"]),
    C(F.bilinear, (r(3, 4), r(3, 5), r(2, 4, 5)),
      ref=t_ref(lambda a, b, w: tF.bilinear(a, b, w)), op_types=["bilinear"]),
    C(F.embedding, (np.array([0, 2, 1]), r(5, 4)),
      ref=lambda i, w: w[i], op_types=["lookup_table_v2"]),
    C(F.layer_norm, (r(3, 4), [4], r(4), r(4)),
      ref=t_ref(lambda x, s, w, b: tF.layer_norm(x, [4], w, b)),
      grad=(0,), op_types=["layer_norm"]),
    C(F.label_smooth, (np.eye(4, dtype="float32"),), op_types=["label_smooth"]),
    C(paddle.dist, (r(3, 4), r(3, 4)),
      ref=lambda x, y: np.linalg.norm((x - y).ravel()), op_types=[]),
]

ALL_CASES = UNARY + BINARY + REDUCE + LINALG + MANIP + SEARCH + LOGIC + ACT + LOSS

# traced/eager parity (the TPU performance path) for the core families;
# random ops (gumbel_softmax) draw different keys eager vs traced
for _c in UNARY + BINARY + REDUCE + ACT:
    if _c.name not in ("gumbel_softmax", "rrelu", "dropout"):
        _c.check_jit = True


@pytest.mark.parametrize(
    "case", ALL_CASES,
    ids=[f"{i}:{c.name}" for i, c in enumerate(ALL_CASES)])
def test_op_case(case):
    run_case(case)


# Ops verified by other test files or not meaningfully coverable by the
# value-oracle harness (random, distributed, compound-model, infra ops).
# Mirrors the reference's white_list/ exemption pattern.
EXEMPT = {
    # random ops: distribution checked in test_ops.py::test_creation_ops
    "dropout", "rrelu", "gumbel_softmax",
    # conv/pool/rnn/attention: exercised in test_nn.py against torch
    "conv2d", "conv2d_transpose", "pool_avg", "pool_max", "adaptive_pool",
    "unfold", "interpolate", "pixel_shuffle", "local_response_norm",
    "rnn_scan_gru", "rnn_scan_lstm", "rnn_scan_simple", "gru_cell",
    "lstm_cell", "simple_rnn_cell", "scaled_dot_product_attention",
    "flash_attention",  # registered lazily by ops.pallas; engaged in test_nn
    "flash_attention_hm",  # heads-major variant; parity in test_nn gpt test
    # packed head-pair variant (d=64): parity in tests/test_packed_flash.py
    # (TPU) + gate/fallback coverage in test_nn on CPU
    "packed_flash_attention",
    "batch_norm_train", "batch_norm_infer", "group_norm", "instance_norm",
    # fused bn+(add+)relu: parity vs composed path (fwd+grads, eager+jit)
    # in test_nn.py::test_fused_bn_act_matches_composed
    "fused_bn_add_act_train",
    "ctc_loss", "cross_entropy_probs",
    # distributed/SPMD ops: test_distributed.py
    "c_allgather", "c_allreduce", "c_alltoall", "c_broadcast", "c_ppermute",
    "c_reducescatter", "axis_index", "shard_constraint",
    # in-place/indexing infra: test_autograd.py / test_ops.py
    "set_value", "getitem", "slice", "strided_slice", "increment", "scale",
    "clip", "lerp", "add_n", "pad_nd",
}


# Explicit snapshot of ops exercised by tests/test_op_tail.py (and
# test_math_tail). A NEW op registered anywhere must be added to a test
# AND listed here (or given OpTestCase coverage above) — the gate stays
# closed by default.
TAIL_COVERED = {
    'accuracy', 'adadelta', 'adagrad', 'adam', 'adamax', 'adamw',
    'affine_grid', 'assign_value', 'auc', 'beam_search', 'bernoulli',
    'box_coder', 'bpr_loss', 'broadcast_tensors', 'center_loss',
    'check_finite_and_unscale', 'coalesce_tensor', 'conditional_block',
    'conv_shift', 'cos_sim', 'crf_decoding', 'crop_tensor', 'cvm',
    'data_norm', 'decayed_adagrad', 'dequantize_linear', 'dirichlet',
    'exponential', 'fake_channel_wise_quantize_abs_max',
    'fake_channel_wise_quantize_dequantize_abs_max',
    'fake_quantize_abs_max', 'fake_quantize_dequantize_abs_max',
    'fake_quantize_dequantize_moving_average_abs_max',
    'fake_quantize_moving_average_abs_max', 'fft2_c2c', 'fft2_c2c_inv',
    'fft2_c2r', 'fft2_r2c', 'fft_c2c', 'fft_c2c_inv', 'fft_c2r',
    'fft_c2r_h', 'fft_ishift', 'fft_r2c', 'fft_r2c_ih', 'fft_shift',
    'fftn_c2c', 'fftn_c2c_inv', 'fftn_c2r', 'fftn_r2c', 'fold', 'fsp',
    'ftrl', 'fused_attention', 'fused_bias_dropout_residual_layer_norm',
    'fused_bn_act', 'fused_elemwise_activation',
    'fused_embedding_seq_pool', 'fused_feedforward',
    'fused_gemm_epilogue', 'fusion_gru', 'fusion_lstm',
    'fusion_repeated_fc_relu', 'fusion_seqpool_concat', 'gather_tree',
    'grid_sampler', 'hinge_loss', 'iou_similarity', 'l1_norm', 'lamb',
    'lars_momentum', 'linear_chain_crf', 'meshgrid', 'minus', 'momentum',
    'moving_average_abs_max_scale', 'mul', 'multinomial', 'multiplex',
    'pad_constant_like', 'partial_concat', 'partial_sum',
    'pixel_unshuffle', 'poisson', 'prior_box', 'quantize_linear',
    'rank_loss', 'rmsprop', 'roi_align', 'roi_pool', 'row_conv',
    'sample_logits', 'sampling_id', 'segment_pool_max',
    'segment_pool_min', 'segment_pool_sum', 'sequence_mask',
    'sequence_pad', 'sequence_pool', 'sequence_reverse',
    'sequence_softmax', 'sgd', 'shape', 'shuffle_batch',
    'shuffle_channel', 'sigmoid_focal_loss', 'size', 'space_to_depth',
    'spectral_norm', 'squared_l2_norm', 'standard_gamma', 'switch_case',
    'temporal_shift', 'truncated_gaussian_random', 'unbind', 'unique',
    'unpool', 'update_loss_scaling', 'viterbi_decode', 'while',
    'yolo_box',
    # math tail (test_op_tail.py::test_math_tail)
    'complex', 'polar', 'logit', 'diff', 'trapezoid',
    'cumulative_trapezoid', 'vander', 'renorm', 'take', 'nan_to_num',
    'signbit', 'ldexp', 'frexp', 'sync_batch_norm',
    # round-3 op-tail (tests/test_op_tail3.py + test_op_coverage.py gate)
    'add_position_encoding', 'affine_channel', 'anchor_generator',
    'average_accumulates', 'batch_fc', 'bilateral_slice',
    'bilinear_tensor_product', 'box_clip', 'correlation', 'ctc_align',
    'deformable_conv', 'deformable_psroi_pooling', 'dequantize',
    'dequantize_abs_max',
    'dequantize_log', 'diag_embed', 'dpsgd',
    'fake_channel_wise_dequantize_max_abs', 'fake_quantize_range_abs_max',
    'fusion_squared_mat_sub', 'gru_unit', 'hash',
    'hierarchical_sigmoid', 'lstm_unit', 'lstmp', 'match_matrix_tensor',
    'mean_iou', 'modified_huber_loss', 'multihead_matmul', 'nce',
    'polygon_box_transform', 'precision_recall', 'proximal_adagrad',
    'proximal_gd', 'prroi_pool', 'psroi_pool', 'quantize', 'requantize',
    'sequence_concat', 'sequence_conv', 'sequence_enumerate',
    'sequence_scatter', 'sequence_topk_avg_pooling', 'skip_layernorm',
    'squared_l2_distance', 'target_assign', 'teacher_student_sigmoid_loss',
    'tensor_array_to_tensor', 'var_conv_2d', 'yolov3_loss',
}


def test_every_registered_op_is_covered():
    from paddle_tpu.core.dispatch import registered_ops, get_op
    covered = set(EXEMPT) | TAIL_COVERED
    for c in ALL_CASES:
        covered.update(c.op_types)
    covered_fns = {id(get_op(n).raw_fn) for n in covered
                   if get_op(n) is not None}
    missing = []
    for o in registered_ops():
        if o in covered:
            continue
        fn = get_op(o)
        # alias of a covered op (same kernel object) counts as covered
        if id(fn.raw_fn) in covered_fns:
            continue
        missing.append(o)
    assert not missing, f"ops with no harness coverage: {missing}"
