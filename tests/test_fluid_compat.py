"""The legacy fluid namespace: reference-era user code must run as-is
(`import paddle.fluid as fluid` style, reference python/paddle/fluid/).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def test_fluid_static_book_flow(tmp_path):
    """The reference book-test shape (test_recognize_digits style):
    build a program with fluid.layers, train with fluid.Executor,
    save/load persistables through fluid.io."""
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=8, act="relu")
            logits = fluid.layers.fc(h, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            import paddle_tpu.optimizer as opt
            opt.SGD(0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 4).astype(np.float32)
        ys = (xs.sum(1, keepdims=True) > 0).astype(np.int64) * 2
        losses = []
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.8, losses
        fluid.io.save_persistables(exe, str(tmp_path))
        fluid.io.load_persistables(exe, str(tmp_path))
        (lv2,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv2)))
    finally:
        paddle.disable_static()


def test_fluid_dygraph_flow():
    paddle.seed(0)
    with fluid.dygraph.guard():
        lin = fluid.dygraph.Linear(4, 2, act="relu")
        emb = fluid.dygraph.Embedding(size=[10, 4])
        ids = fluid.dygraph.to_variable(
            np.array([[1, 2], [3, 4]], np.int64))
        out = lin(emb(ids))
        assert list(out.shape) == [2, 2, 2]
        assert (out.numpy() >= 0).all()  # relu fused
        out.backward()
        assert emb.weight.grad is not None


def test_fluid_core_ops_and_misc():
    # core.ops.<op> fast-path callables (op_function_generator analogue)
    import jax.numpy as jnp
    r = fluid.core.ops.relu(jnp.asarray(np.array([-1.0, 2.0], np.float32)))
    np.testing.assert_allclose(np.asarray(r), [0.0, 2.0])
    assert "relu" in dir(fluid.core.ops)
    assert fluid.core.is_compiled_with_xpu() is False
    assert isinstance(fluid.core.Scope(), fluid.Scope)
    # layers delegation breadth: tensor/math/control-flow names resolve
    for name in ("concat", "reshape", "reduce_sum", "elementwise_add",
                 "fill_constant", "cast", "while_loop", "cond", "topk",
                 "softmax", "relu", "cross_entropy", "fc", "StaticRNN"):
        assert callable(getattr(fluid.layers, name)), name
    fluid.require_version("1.8.0")
    # save/load_dygraph round trip
    lin = fluid.dygraph.Linear(3, 2)
    import tempfile, os
    d = tempfile.mkdtemp()
    fluid.dygraph.save_dygraph(lin.state_dict(), os.path.join(d, "m"))
    params, opt = fluid.dygraph.load_dygraph(os.path.join(d, "m"))
    assert params is not None and "_linear.weight" in params


def test_fluid_save_load_inference_model(tmp_path):
    """fluid-era signature: feed by NAME, artifact under dirname."""
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "inf")
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
        res = fluid.io.load_inference_model(d, exe)
        assert res is not None
    finally:
        paddle.disable_static()


def test_dynamic_decode_minimal_decoder_and_impute():
    """A Decoder subclass without finalize must work (reference wraps
    finalize in try/except NotImplementedError); impute_finished freezes
    finished beams' states."""
    import paddle_tpu.nn as nn

    class CountDecoder(nn.Decoder):
        def initialize(self, inits):
            z = paddle.to_tensor(np.zeros((2,), np.float32))
            return z, z, paddle.to_tensor(np.array([False, False]))

        def step(self, time, inputs, states, **kwargs):
            nxt = states + 1.0
            fin = paddle.to_tensor(np.array([time >= 1, time >= 2]))
            return {"out": nxt}, nxt, nxt, fin

    outs, states = nn.dynamic_decode(CountDecoder(), max_step_num=4)
    assert outs["out"].shape[1] == 3  # stopped when all finished (t=2)

    paddle.seed(0)
    cell = paddle.nn.GRUCell(4, 8)
    emb = paddle.nn.Embedding(6, 4)
    proj = paddle.nn.Linear(8, 6)
    dec = nn.BeamSearchDecoder(cell, 0, 1, 2, embedding_fn=emb,
                               output_fn=proj)
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(2, 8)
                          .astype(np.float32))
    o1, s1 = nn.dynamic_decode(dec, inits=h0, max_step_num=6,
                               impute_finished=True)
    assert o1["predicted_ids"].numpy().shape[0] == 2
