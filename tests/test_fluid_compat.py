"""The legacy fluid namespace: reference-era user code must run as-is
(`import paddle.fluid as fluid` style, reference python/paddle/fluid/).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def test_fluid_static_book_flow(tmp_path):
    """The reference book-test shape (test_recognize_digits style):
    build a program with fluid.layers, train with fluid.Executor,
    save/load persistables through fluid.io."""
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=8, act="relu")
            logits = fluid.layers.fc(h, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            import paddle_tpu.optimizer as opt
            opt.SGD(0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 4).astype(np.float32)
        ys = (xs.sum(1, keepdims=True) > 0).astype(np.int64) * 2
        losses = []
        for _ in range(20):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.8, losses
        fluid.io.save_persistables(exe, str(tmp_path))
        fluid.io.load_persistables(exe, str(tmp_path))
        (lv2,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv2)))
    finally:
        paddle.disable_static()


def test_fluid_dygraph_flow():
    paddle.seed(0)
    with fluid.dygraph.guard():
        lin = fluid.dygraph.Linear(4, 2, act="relu")
        emb = fluid.dygraph.Embedding(size=[10, 4])
        ids = fluid.dygraph.to_variable(
            np.array([[1, 2], [3, 4]], np.int64))
        out = lin(emb(ids))
        assert list(out.shape) == [2, 2, 2]
        assert (out.numpy() >= 0).all()  # relu fused
        out.backward()
        assert emb.weight.grad is not None


def test_fluid_core_ops_and_misc():
    # core.ops.<op> fast-path callables (op_function_generator analogue)
    import jax.numpy as jnp
    r = fluid.core.ops.relu(jnp.asarray(np.array([-1.0, 2.0], np.float32)))
    np.testing.assert_allclose(np.asarray(r), [0.0, 2.0])
    assert "relu" in dir(fluid.core.ops)
    assert fluid.core.is_compiled_with_xpu() is False
    assert isinstance(fluid.core.Scope(), fluid.Scope)
    # layers delegation breadth: tensor/math/control-flow names resolve
    for name in ("concat", "reshape", "reduce_sum", "elementwise_add",
                 "fill_constant", "cast", "while_loop", "cond", "topk",
                 "softmax", "relu", "cross_entropy", "fc", "StaticRNN"):
        assert callable(getattr(fluid.layers, name)), name
    fluid.require_version("1.8.0")
    # save/load_dygraph round trip
    lin = fluid.dygraph.Linear(3, 2)
    import tempfile, os
    d = tempfile.mkdtemp()
    fluid.dygraph.save_dygraph(lin.state_dict(), os.path.join(d, "m"))
    params, opt = fluid.dygraph.load_dygraph(os.path.join(d, "m"))
    assert params is not None and "_linear.weight" in params


def test_fluid_save_load_inference_model(tmp_path):
    """fluid-era signature: feed by NAME, artifact under dirname."""
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "inf")
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
        res = fluid.io.load_inference_model(d, exe)
        assert res is not None
    finally:
        paddle.disable_static()


def test_dynamic_decode_minimal_decoder_and_impute():
    """A Decoder subclass without finalize must work (reference wraps
    finalize in try/except NotImplementedError); impute_finished freezes
    finished beams' states."""
    import paddle_tpu.nn as nn

    class CountDecoder(nn.Decoder):
        def initialize(self, inits):
            z = paddle.to_tensor(np.zeros((2,), np.float32))
            return z, z, paddle.to_tensor(np.array([False, False]))

        def step(self, time, inputs, states, **kwargs):
            nxt = states + 1.0
            fin = paddle.to_tensor(np.array([time >= 1, time >= 2]))
            return {"out": nxt}, nxt, nxt, fin

    outs, states = nn.dynamic_decode(CountDecoder(), max_step_num=4)
    assert outs["out"].shape[1] == 3  # stopped when all finished (t=2)

    paddle.seed(0)
    cell = paddle.nn.GRUCell(4, 8)
    emb = paddle.nn.Embedding(6, 4)
    proj = paddle.nn.Linear(8, 6)
    dec = nn.BeamSearchDecoder(cell, 0, 1, 2, embedding_fn=emb,
                               output_fn=proj)
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(2, 8)
                          .astype(np.float32))
    o1, s1 = nn.dynamic_decode(dec, inits=h0, max_step_num=6,
                               impute_finished=True)
    assert o1["predicted_ids"].numpy().shape[0] == 2


def _reference_fluid_layers_names():
    import ast, os
    base = "/root/reference/python/paddle/fluid/layers"
    names = set()
    for fn in os.listdir(base):
        if not fn.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(base, fn)).read())
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets):
                try:
                    names.update(n for n in ast.literal_eval(node.value)
                                 if not n.startswith("_"))
                except ValueError:
                    pass
    return names


def test_fluid_layers_namespace_parity():
    """Every name the reference exports from fluid.layers (the union of
    all its submodules' __all__, 307 names) resolves here — or is in
    layers_adapters.NOT_PROVIDED with a documented reason."""
    from paddle_tpu.fluid.layers_adapters import NOT_PROVIDED
    names = _reference_fluid_layers_names()
    missing = sorted(n for n in names
                     if not hasattr(fluid.layers, n)
                     and n not in NOT_PROVIDED)
    assert not missing, f"fluid.layers names unaccounted: {missing}"
    stale = sorted(n for n in NOT_PROVIDED if n not in names)
    assert not stale, f"NOT_PROVIDED entries not in reference: {stale}"
    dead = sorted(n for n in NOT_PROVIDED if hasattr(fluid.layers, n))
    assert not dead, \
        f"NOT_PROVIDED entries that actually resolve (stale doc): {dead}"


def test_fluid_layers_adapters_behave():
    import math
    x = paddle.to_tensor(np.array([[1.0, -2.0], [3.0, 4.0]], np.float32))
    # activations
    np.testing.assert_allclose(
        fluid.layers.hard_sigmoid(x, 0.2, 0.5).numpy(),
        np.clip(0.2 * x.numpy() + 0.5, 0, 1), rtol=1e-6)
    np.testing.assert_allclose(
        fluid.layers.brelu(x, 0.0, 3.0).numpy(),
        np.clip(x.numpy(), 0.0, 3.0), rtol=1e-6)
    # losses
    h = fluid.layers.huber_loss(x, paddle.zeros_like(x), delta=1.0)
    np.testing.assert_allclose(h.numpy()[0, 0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(h.numpy()[1, 1], 1.0 * (4 - 0.5), rtol=1e-6)
    sl1 = fluid.layers.smooth_l1(x, paddle.zeros_like(x))
    assert sl1.shape == [2, 1]
    # elementwise with fluid axis
    y = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
    out = fluid.layers.elementwise_mul(
        paddle.to_tensor(np.ones((2, 2, 3), np.float32)), y, axis=0)
    np.testing.assert_allclose(out.numpy()[:, 0, 0], [10.0, 20.0])
    # reduce_all/any
    b = paddle.to_tensor(np.array([[True, False], [True, True]]))
    assert fluid.layers.reduce_all(b, dim=1).numpy().tolist() == \
        [False, True]
    # lr schedule adapters return working schedulers
    sched = fluid.layers.noam_decay(128, 100)
    import paddle_tpu.optimizer as opt
    assert isinstance(sched, opt.lr.LRScheduler)
    # ctc greedy decode: merge repeats, strip blanks
    probs = np.zeros((1, 5, 3), np.float32)
    for t, c in enumerate([1, 1, 0, 2, 2]):
        probs[0, t, c] = 5.0
    dec, lens = fluid.layers.ctc_greedy_decoder(
        paddle.to_tensor(probs), blank=0)
    assert dec.numpy()[0, :int(lens.numpy()[0])].tolist() == [1, 2]
    # beam_search one step
    pre_ids = paddle.to_tensor(np.zeros((2, 1), np.int64))
    pre_sc = paddle.to_tensor(np.zeros((2, 1), np.float32))
    sc = paddle.to_tensor(np.log(np.array(
        [[0.1, 0.6, 0.3], [0.5, 0.2, 0.3]], np.float32)))
    ids, scs = fluid.layers.beam_search(pre_ids, pre_sc, None, sc,
                                        beam_size=2, end_id=0)
    assert ids.shape == [2, 1]
    # MultivariateNormalDiag entropy/kl
    mvn = paddle.distribution.MultivariateNormalDiag(
        [0.0, 0.0], np.diag([1.0, 1.0]).astype(np.float32))
    want = 0.5 * (2 * (1 + math.log(2 * math.pi)))
    np.testing.assert_allclose(float(mvn.entropy().numpy()), want,
                               rtol=1e-5)
    mvn2 = paddle.distribution.MultivariateNormalDiag(
        [1.0, 0.0], np.diag([2.0, 1.0]).astype(np.float32))
    kl = float(mvn.kl_divergence(mvn2).numpy())
    want_kl = 0.5 * ((0.5 + 1.0) + (0.5 + 0.0) - 2 + math.log(2.0))
    np.testing.assert_allclose(kl, want_kl, rtol=1e-5)


def test_basic_decoder_helpers():
    paddle.seed(0)
    cell = paddle.nn.GRUCell(4, 8)
    proj = paddle.nn.Linear(8, 5)
    emb = paddle.nn.Embedding(5, 4)
    helper = paddle.nn.GreedyEmbeddingHelper(
        emb, np.zeros(3, np.int64), end_token=1)
    dec = paddle.nn.BasicDecoder(cell, helper, output_fn=proj)
    h0 = paddle.to_tensor(np.random.RandomState(0).randn(3, 8)
                          .astype(np.float32))
    outs, states = paddle.nn.dynamic_decode(dec, inits=h0, max_step_num=5)
    assert outs["sample_ids"].numpy().shape[0] == 3
    # training helper follows the ground-truth sequence
    gt = paddle.to_tensor(np.random.RandomState(1)
                          .randn(3, 4, 4).astype(np.float32))
    th = paddle.nn.TrainingHelper(gt)
    dec2 = paddle.nn.BasicDecoder(cell, th, output_fn=proj)
    outs2, _ = paddle.nn.dynamic_decode(dec2, inits=h0, max_step_num=10)
    assert outs2["cell_outputs"].numpy().shape[1] == 4  # stops at T


def test_dynamic_decode_finished_accumulates():
    """A sequence that emitted end_token must STAY finished even if a
    later step's sample is not end_token (review repro: decode used to
    run to max_step_num because finished could un-set)."""
    import paddle_tpu.nn as nn

    class FlipFlop(nn.Decoder):
        # seq0 "finishes" at t=0 then would report unfinished at t>=1
        def initialize(self, inits):
            z = paddle.to_tensor(np.zeros(2, np.float32))
            return z, z, paddle.to_tensor(np.array([False, False]))

        def step(self, time, inputs, states, **kwargs):
            fin = paddle.to_tensor(np.array([time == 0, time >= 2]))
            return {"o": states}, states, inputs, fin

    outs, _ = nn.dynamic_decode(FlipFlop(), max_step_num=10)
    assert outs["o"].numpy().shape[1] == 3  # stops at t=2, not 10


def test_beam_search_freezes_finished_and_global_parents():
    # beam 0 of each batch row already ended; it must only extend with
    # end_id at its pre_score, and parent indices must be GLOBAL rows
    end_id = 0
    pre_ids = paddle.to_tensor(
        np.array([[end_id], [5], [end_id], [5]], np.int64))
    pre_sc = paddle.to_tensor(
        np.array([[1.5], [0.5], [2.5], [0.1]], np.float32))
    sc = paddle.to_tensor(np.log(np.tile(np.array(
        [[0.1, 0.6, 0.3]], np.float32), (4, 1))) )
    ids, scs, parents = fluid.layers.beam_search(
        pre_ids, pre_sc, None, sc + pre_sc, beam_size=2, end_id=end_id,
        return_parent_idx=True)
    ids, scs, parents = ids.numpy(), scs.numpy(), parents.numpy()
    # batch 0: frozen beam (row 0, score 1.5 with token end_id) must win
    assert ids[0, 0] == end_id and abs(scs[0, 0] - 1.5) < 1e-5
    # batch 1 parents point at global rows 2..3, not 0..1
    assert parents[2] >= 2 and parents[3] >= 2


def test_fluid_data_negative_dims():
    paddle.enable_static()
    try:
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            v = fluid.layers.data("a", shape=[3, -1])
            assert list(v.shape) == [3, -1]  # NOT [-1, 3, -1]
            w = fluid.layers.data("b", shape=[4])
            assert list(w.shape) == [-1, 4]
    finally:
        paddle.disable_static()
