"""KV-cache autoregressive generation — parity with the Layer forward.

The decode implementation mirrors GPT.forward in pure jax; these tests
pin the two together so they cannot drift.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.models.generation import (decode_step, extract_params,
                                          generate, prefill)


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=24)
    m = GPT(cfg)
    m.eval()
    geom = (cfg.num_layers, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, cfg.max_seq_len)
    return m, geom


def test_prefill_matches_layer_forward():
    m, geom = _model()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (2, 10))
    logits, cache = prefill(extract_params(m), jnp.asarray(ids, jnp.int32),
                            geom)
    full = m(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(np.asarray(logits), full[:, -1],
                               rtol=1e-4, atol=1e-4)


def test_cached_decode_matches_full_forward_per_step():
    """Each cached step's logits == the full forward's last position on
    the growing sequence — the KV cache is exact, not approximate."""
    m, geom = _model()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 97, (1, 6))
    params = extract_params(m)
    logits, cache = prefill(params, jnp.asarray(ids, jnp.int32), geom)
    seq = ids.copy()
    for step in range(5):
        tok = np.argmax(np.asarray(logits), axis=-1)
        seq = np.concatenate([seq, tok[:, None]], axis=1)
        logits, cache = decode_step(params, cache,
                                    jnp.asarray(tok, jnp.int32),
                                    jnp.asarray(seq.shape[1] - 1,
                                                jnp.int32), geom)
        full = m(paddle.to_tensor(seq)).numpy()[:, -1]
        np.testing.assert_allclose(np.asarray(logits), full,
                                   rtol=1e-4, atol=1e-4)


def test_greedy_generate_matches_full_rollout():
    m, geom = _model()
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 97, (2, 5))
    out = generate(m, ids, max_new_tokens=6)
    assert out.shape == (2, 11)
    # oracle: repeated full forwards + argmax
    seq = ids.copy()
    for _ in range(6):
        nxt = np.argmax(m(paddle.to_tensor(seq)).numpy()[:, -1], axis=-1)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_sampled_generate_runs_and_respects_budget():
    m, geom = _model()
    ids = np.zeros((1, 4), np.int64)
    out = generate(m, ids, max_new_tokens=8, temperature=0.8, top_k=5,
                   seed=3)
    assert out.shape == (1, 12)
    assert (out[:, :4] == 0).all()
    with pytest.raises(ValueError):
        generate(m, np.zeros((1, 20), np.int64), max_new_tokens=10)


def test_generate_eos_token_stops_and_pads():
    """eos_token_id: once greedy emits the eos, every later position is
    frozen to eos (static shapes — the scan still runs max_new steps,
    masked); tokens before the eos are untouched."""
    m, geom = _model()
    rng = np.random.RandomState(8)
    ids = rng.randint(0, 97, (2, 4))
    free = np.asarray(generate(m, ids, max_new_tokens=8))
    eos = int(free[0, 4 + 2])                # row 0's 3rd greedy token
    out = np.asarray(generate(m, ids, max_new_tokens=8,
                              eos_token_id=eos))
    assert out.shape == free.shape
    for r in range(2):
        row, ref = out[r, 4:], free[r, 4:]
        hits = np.nonzero(ref == eos)[0]
        if hits.size:                        # row 0 by construction
            k = hits[0]
            np.testing.assert_array_equal(row[:k + 1], ref[:k + 1])
            assert (row[k:] == eos).all()
        else:
            np.testing.assert_array_equal(row, ref)
    assert (out[0, 4 + 2:] == eos).all()


def test_generate_top_p_one_is_bitwise_plain_temperature():
    """top_p=1.0 must compile to the EXACT plain-temperature program —
    the nucleus mask drops at trace time, so the sampled ids are
    bitwise-identical to not passing top_p at all."""
    m, geom = _model()
    ids = np.zeros((2, 4), np.int64)
    plain = np.asarray(generate(m, ids, max_new_tokens=10,
                                temperature=0.8, seed=5))
    nucleus = np.asarray(generate(m, ids, max_new_tokens=10,
                                  temperature=0.8, top_p=1.0, seed=5))
    np.testing.assert_array_equal(plain, nucleus)


def test_generate_top_p_tiny_collapses_to_greedy():
    """top_p -> 0 keeps only the top-ranked token (the rank-0 prefix is
    always kept), so sampling at any temperature becomes greedy."""
    m, geom = _model()
    rng = np.random.RandomState(9)
    ids = rng.randint(0, 97, (2, 4))
    greedy = np.asarray(generate(m, ids, max_new_tokens=8))
    sampled = np.asarray(generate(m, ids, max_new_tokens=8,
                                  temperature=1.3, top_p=1e-6, seed=11))
    np.testing.assert_array_equal(sampled, greedy)


def test_generate_top_p_restricts_support():
    """With a mid top_p the sampled tokens stay inside the nucleus of
    the step distribution (checked on the first sampled position)."""
    m, geom = _model()
    ids = np.zeros((1, 4), np.int64)
    logits = m(paddle.to_tensor(ids)).numpy()[0, -1].astype(np.float64)
    lg = logits / 0.9
    srt = np.sort(lg)[::-1]
    probs = np.exp(srt - srt.max())
    probs /= probs.sum()
    keep = int(((np.cumsum(probs) - probs) < 0.7).sum())
    nucleus = set(np.argsort(lg)[::-1][:keep].tolist())
    firsts = {int(np.asarray(generate(
        m, ids, max_new_tokens=1, temperature=0.9, top_p=0.7,
        seed=s))[0, 4]) for s in range(12)}
    assert firsts <= nucleus


def test_beam_search_beam1_equals_greedy():
    m, geom = _model()
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 97, (2, 5))
    greedy = generate(m, ids, max_new_tokens=6)
    from paddle_tpu.models.generation import beam_search_generate
    beam, scores = beam_search_generate(m, ids, beam_size=1,
                                        max_new_tokens=6)
    np.testing.assert_array_equal(beam, greedy)
    assert scores.shape == (2,)


def test_beam_search_finds_higher_likelihood_than_greedy():
    """The point of beam search: sum-logprob of the beam-4 output must be
    >= the greedy rollout's (checked under the true model logprobs)."""
    m, geom = _model()
    rng = np.random.RandomState(5)
    ids = rng.randint(0, 97, (1, 4))
    steps = 8
    from paddle_tpu.models.generation import beam_search_generate
    beam, beam_score = beam_search_generate(m, ids, beam_size=4,
                                            max_new_tokens=steps)
    greedy = generate(m, ids, max_new_tokens=steps)

    def seq_logprob(seq):
        total = 0.0
        for s in range(steps):
            cur = seq[:, :ids.shape[1] + s]
            logits = m(paddle.to_tensor(cur)).numpy()[:, -1]
            lp = logits - np.log(np.exp(
                logits - logits.max(-1, keepdims=True)).sum(
                -1, keepdims=True)) - logits.max(-1, keepdims=True)
            total += lp[0, seq[0, ids.shape[1] + s]]
        return total

    assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-4
    np.testing.assert_allclose(seq_logprob(beam), beam_score[0],
                               rtol=1e-3, atol=1e-3)


def test_beam_search_eos_freezes_score():
    m, geom = _model()
    ids = np.zeros((1, 3), np.int64)
    from paddle_tpu.models.generation import beam_search_generate
    out, scores = beam_search_generate(m, ids, beam_size=3,
                                       max_new_tokens=8, eos_token_id=7)
    # once 7 appears in a row, everything after must be 7 (frozen beam)
    row = out[0, 3:]
    if 7 in row:
        first = list(row).index(7)
        assert (row[first:] == 7).all()


def test_exported_decoder_serves_without_model(tmp_path):
    """export_decoder → DecoderPredictor: greedy generation from the
    serialized StableHLO pair matches running generate() on a prompt of
    exactly the exported prefill length (no model class at serve time)."""
    from paddle_tpu.models.generation import (DecoderPredictor,
                                              export_decoder)
    m, geom = _model()
    export_decoder(m, str(tmp_path / "gpt"))
    pred = DecoderPredictor(str(tmp_path / "gpt"))

    rng = np.random.RandomState(6)
    Tp = pred.prefill_len
    ids = rng.randint(1, 97, (2, Tp))
    served = pred.generate(ids, max_new_tokens=5)
    direct = generate(m, ids, max_new_tokens=5)
    np.testing.assert_array_equal(served, direct)

    with pytest.raises(ValueError):
        pred.generate(np.zeros((1, Tp + 1), np.int64), 2)


def test_beam_length_penalty_normalizes_per_hypothesis():
    """length_penalty divides each beam by ITS OWN hypothesis length
    (reference beam_search_op semantics; a uniform divisor could never
    change the argmax). Verified arithmetically: the returned score must
    equal the winner's raw model logprob (up to and including its first
    eos) divided by that hypothesis's length."""
    from paddle_tpu.models.generation import beam_search_generate
    m, geom = _model()
    ids = np.zeros((1, 3), np.int64)
    T, steps = 3, 8
    out1, s1 = beam_search_generate(m, ids, beam_size=4,
                                    max_new_tokens=steps,
                                    eos_token_id=7, length_penalty=1.0)
    assert np.isfinite(s1).all()
    row = out1[0, T:]
    n_real = (list(row).index(7) + 1) if 7 in row else steps

    raw = 0.0
    for s in range(n_real):  # logprob of tokens up to + incl. first eos
        cur = out1[:, :T + s]
        logits = m(paddle.to_tensor(cur)).numpy()[:, -1].astype(np.float64)
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        raw += lp[0, row[s]]
    np.testing.assert_allclose(s1[0], raw / (T + n_real), rtol=1e-3,
                               atol=1e-3)
