"""Device-resident embedding cache (C37 — PSGPU/ps_gpu_wrapper.cc
analogue): HBM-resident hot rows with on-device optimizer updates must be
semantically invisible vs the pure-host PS path.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (DeviceEmbeddingCache,
                                       ParameterServer, PsClient)


def _mk_server(dim=8, optimizer="adagrad", lr=0.1, seed=7, vocab=64):
    rng = np.random.RandomState(seed)
    server = ParameterServer(port=0)
    server.add_sparse_table(
        0, dim=dim, optimizer=optimizer, lr=lr,
        initializer=lambda: rng.normal(0, 0.01, dim).astype(np.float32))
    server.start()
    client = PsClient([server.endpoint])
    # lazy-init consumes the rng in touch order; touch every row in a
    # fixed order so two servers hold identical initial tables
    client.pull_sparse(0, np.arange(vocab, dtype=np.int64))
    return server, client


def _run_steps(client, cache, steps, dim, vocab, seed=3):
    """A tiny CTR-ish loop: pull rows, loss = mean(rows**2), push grads."""
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        ids = rng.randint(0, vocab, 32)
        uniq, inv = np.unique(ids, return_inverse=True)
        if cache is not None:
            rows = np.asarray(cache.pull(uniq))
        else:
            rows = np.asarray(client.pull_sparse(0, uniq))
        # emulate an embedding-bag forward/backward with duplicates
        vecs = rows[inv]
        losses.append(float((vecs ** 2).mean()))
        g = 2.0 * vecs / vecs.size
        grad_rows = np.zeros_like(rows)
        np.add.at(grad_rows, inv, g)
        if cache is not None:
            cache.push(uniq, grad_rows)
        else:
            client.push_sparse(0, uniq, grad_rows)
    return losses


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad"])
def test_device_cache_matches_host_ps(optimizer):
    """Full-coverage cache (every id hot): loss sequence must equal the
    pure-host PS run step for step — the device optimizer rule is the
    same arithmetic as table.py's."""
    dim, vocab = 8, 64
    s1, c1 = _mk_server(dim, optimizer)
    s2, c2 = _mk_server(dim, optimizer)
    try:
        host_losses = _run_steps(c1, None, 10, dim, vocab)
        cache = DeviceEmbeddingCache(c2, 0, cache_rows=vocab, dim=dim,
                                     optimizer=optimizer, lr=0.1)
        dev_losses = _run_steps(c2, cache, 10, dim, vocab)
        np.testing.assert_allclose(dev_losses, host_losses, rtol=1e-5)
        assert cache.host_pulls == 0  # everything rode HBM
    finally:
        s1.stop(), s2.stop()


def test_device_cache_mixed_hot_cold_parity():
    """Cache covering only part of the vocab: cold ids ride the PS, hot
    ids the device — combined semantics must still match pure host."""
    dim, vocab = 8, 64
    s1, c1 = _mk_server(dim)
    s2, c2 = _mk_server(dim)
    try:
        host_losses = _run_steps(c1, None, 10, dim, vocab)
        cache = DeviceEmbeddingCache(c2, 0, cache_rows=vocab // 2, dim=dim,
                                     optimizer="adagrad", lr=0.1)
        dev_losses = _run_steps(c2, cache, 10, dim, vocab)
        np.testing.assert_allclose(dev_losses, host_losses, rtol=1e-5)
        assert cache.host_pulls > 0  # the cold tail was actually exercised
    finally:
        s1.stop(), s2.stop()


def test_device_cache_flush_round_trip():
    """flush() (the PSGPU EndPass analogue) must land the device-trained
    rows on the PS so save()/checkpoints see them."""
    dim, vocab = 4, 16
    server, client = _mk_server(dim, "sgd")
    try:
        cache = DeviceEmbeddingCache(client, 0, cache_rows=vocab, dim=dim,
                                     optimizer="sgd", lr=0.1)
        _run_steps(client, cache, 5, dim, vocab)
        cache.flush()
        ps_rows = np.asarray(client.pull_sparse(
            0, np.arange(vocab, dtype=np.int64)))
        np.testing.assert_allclose(ps_rows, np.asarray(cache.table),
                                   rtol=1e-6)
    finally:
        server.stop()


def test_device_cache_adagrad_state_continuity():
    """Building the cache over a PRE-TRAINED adagrad table must carry the
    per-row accumulator (the reference ships g2sum with the feature,
    ps_gpu_wrapper.cc) — and flush() must hand it back, so a
    host→device→host trajectory equals pure host."""
    dim, vocab = 8, 64
    s1, c1 = _mk_server(dim, "adagrad")
    s2, c2 = _mk_server(dim, "adagrad")
    try:
        # phase 1: both host-side
        h1 = _run_steps(c1, None, 5, dim, vocab, seed=3)
        h2 = _run_steps(c2, None, 5, dim, vocab, seed=3)
        np.testing.assert_allclose(h1, h2, rtol=1e-6)
        # phase 2: server 2 continues on-device (accumulator must carry)
        cache = DeviceEmbeddingCache(c2, 0, cache_rows=vocab, dim=dim,
                                     optimizer="adagrad", lr=0.1)
        d2 = _run_steps(c2, cache, 5, dim, vocab, seed=11)
        h1b = _run_steps(c1, None, 5, dim, vocab, seed=11)
        np.testing.assert_allclose(d2, h1b, rtol=1e-5)
        # phase 3: flush and resume host-side (state must carry back)
        cache.flush()
        h1c = _run_steps(c1, None, 5, dim, vocab, seed=17)
        h2c = _run_steps(c2, None, 5, dim, vocab, seed=17)
        np.testing.assert_allclose(h2c, h1c, rtol=1e-5)
    finally:
        s1.stop(), s2.stop()


def test_device_cache_negative_ids_go_to_host():
    """Negative ids must not wrap into the device table (jnp indexing
    would silently train a foreign row); they ride the host PS as
    distinct rows, same as the pure-host path."""
    dim = 4
    server, client = _mk_server(dim, "sgd", vocab=8)
    try:
        cache = DeviceEmbeddingCache(client, 0, cache_rows=8, dim=dim,
                                     optimizer="sgd", lr=0.1)
        before = np.asarray(cache.table).copy()
        ids = np.array([-5, 2], np.int64)
        rows = np.asarray(cache.pull(ids))
        assert cache.host_pulls == 1  # -5 went to the PS
        cache.push(ids, np.ones((2, dim), np.float32))
        after = np.asarray(cache.table)
        # only row 2 changed on device; row 8-5=3 (the wrap target) didn't
        changed = np.nonzero(np.abs(after - before).sum(1))[0]
        assert list(changed) == [2]
        # and the PS holds a distinct row keyed -5
        ps_row = np.asarray(client.pull_sparse(0, np.array([-5])))
        np.testing.assert_allclose(ps_row[0], rows[0] - 0.1 * 1.0)
    finally:
        server.stop()


def test_device_cache_rpc_savings():
    """The point of the cache: hot traffic generates no RPCs. Compare RPC
    counts (robust on any backend, unlike wall-clock on a shared CPU)."""
    dim, vocab = 8, 64
    server, client = _mk_server(dim)
    try:
        cache = DeviceEmbeddingCache(client, 0, cache_rows=vocab, dim=dim,
                                     optimizer="adagrad", lr=0.1)
        before = client.stats()[0]["push_count"]
        _run_steps(client, cache, 20, dim, vocab)
        after = client.stats()[0]["push_count"]
        assert after == before  # zero sparse pushes hit the server
        assert cache.host_pulls == 0  # and zero pulls
        _run_steps(client, None, 20, dim, vocab)
        assert client.stats()[0]["push_count"] == before + 20
        # wall-clock is not asserted here: on the 1-core CPU CI box the
        # jitted scatter's dispatch overhead can exceed a loopback RPC;
        # the real-hardware comparison lives in examples/ctr_ps_training
        # --device_cache output
    finally:
        server.stop()
