"""Ring attention (context parallelism) — parity vs full attention.

The long-context mechanism SURVEY.md §2.3 flags: Q sequence-sharded over
a mesh axis, K/V rotating via ppermute, online-softmax accumulation.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.parallel import build_mesh, set_global_mesh, shard_map
from paddle_tpu.parallel.ring_attention import ring_attention


def _full_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _mesh_sp():
    mesh = build_mesh(dp=1, pp=1, tp=1, sp=8, sharding=1)
    set_global_mesh(mesh)
    return mesh


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = _mesh_sp()
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 3, 64, 16
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_vma=False)
    got = np.asarray(f(q, k, v))
    want = np.asarray(_full_attention(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match_full():
    """jax.grad flows through the ppermute rotation; dq/dk/dv must match
    the full-attention gradients."""
    mesh = _mesh_sp()
    rng = np.random.RandomState(1)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)  # cotangent seed

    def ring_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None), check_vma=False)
        return jnp.sum(f(q, k, v) * w)

    def full_loss(q, k, v):
        return jnp.sum(_full_attention(q, k, v, True) * w)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-5, atol=5e-5)


def test_gpt_context_parallel_loss_parity():
    """GPTConfig(context_parallel=True) routes attention through the
    ring over the 'sp' axis; 3-step training losses must match the dense
    attention path on the same mesh."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
    from paddle_tpu.parallel import ShardedTrainStep

    rng = np.random.RandomState(3)
    xs = [rng.randint(0, 128, (4, 32)) for _ in range(3)]
    ys = [rng.randint(0, 128, (4, 32)) for _ in range(3)]

    def run(cp):
        mesh = build_mesh(dp=1, pp=1, tp=1, sp=8, sharding=1)
        set_global_mesh(mesh)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, context_parallel=cp)
        model = GPT(cfg)
        optim = opt.AdamW(1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh)
        return [float(step(paddle.to_tensor(x), paddle.to_tensor(y))
                      .numpy()) for x, y in zip(xs, ys)]

    ring = run(True)
    dense = run(False)
    np.testing.assert_allclose(ring, dense, rtol=2e-4, atol=2e-4)


def test_gpt_context_parallel_composes_with_dp():
    """Partial-manual shard_map (axis_names={'sp'}): dp stays in GSPMD
    auto mode, so ring attention composes with data parallelism instead
    of replicating the batch."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
    from paddle_tpu.parallel import ShardedTrainStep

    rng = np.random.RandomState(4)
    xs = [rng.randint(0, 128, (4, 32)) for _ in range(2)]
    ys = [rng.randint(0, 128, (4, 32)) for _ in range(2)]

    def run(cp):
        mesh = build_mesh(dp=2, pp=1, tp=1, sp=4, sharding=1)
        set_global_mesh(mesh)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, context_parallel=cp)
        model = GPT(cfg)
        optim = opt.AdamW(1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh)
        return [float(step(paddle.to_tensor(x), paddle.to_tensor(y))
                      .numpy()) for x, y in zip(xs, ys)]

    np.testing.assert_allclose(run(True), run(False), rtol=2e-4,
                               atol=2e-4)


def test_ring_attention_bf16_long_sequence():
    """bf16 inputs at a longer sequence: fp32 online accumulation keeps
    the result at bf16 tolerance of the fp32 full-attention oracle."""
    mesh = _mesh_sp()
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 2, 256, 32
    qf = rng.randn(B, H, T, D).astype(np.float32)
    kf = rng.randn(B, H, T, D).astype(np.float32)
    vf = rng.randn(B, H, T, D).astype(np.float32)
    q, k, v = (jnp.asarray(a, jnp.bfloat16) for a in (qf, kf, vf))

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_vma=False)
    got = np.asarray(f(q, k, v)).astype(np.float32)
    want = np.asarray(_full_attention(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), True))
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    """All-to-all (Ulysses) sequence parallelism: heads scatter, sequence
    gathers, full attention per head subset, restore — must equal full
    attention."""
    from paddle_tpu.parallel import ulysses_attention

    mesh = _mesh_sp()
    rng = np.random.RandomState(6)
    B, H, T, D = 2, 8, 64, 16  # H == sp size: one head per device
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)

    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_vma=False)
    got = np.asarray(f(q, k, v))
    want = np.asarray(_full_attention(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_gradients_match_full():
    from paddle_tpu.parallel import ulysses_attention

    mesh = _mesh_sp()
    rng = np.random.RandomState(7)
    B, H, T, D = 1, 8, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)

    def u_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None), check_vma=False)
        return jnp.sum(f(q, k, v) * w)

    def full_loss(q, k, v):
        return jnp.sum(_full_attention(q, k, v, True) * w)

    g_u = jax.grad(u_loss, argnums=(0, 1, 2))(q, k, v)
    g_f = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)
