"""Multi-process distributed test — the TestDistBase analogue.

Reference: fluid/tests/unittests/test_dist_base.py:660 — spawn 2 trainer
subprocesses with the PADDLE_TRAINER_* env contract on free local ports,
then assert their per-step losses match a single-rank run of the same model
on the full batch. Here the subprocesses bootstrap via the JAX coordination
service (init_parallel_env) and the dp allreduce rides Gloo on CPU —
exercising launch.py's env contract end to end.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "dist_mp_model.py")


def _run_cluster(nproc: int, timeout=240, retries=1):
    """One retry on a fresh port (reference TestDistBase retries its
    cluster runs too — rendezvous can flake under parallel CI load)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local CPU device per process
    last = None
    for _ in range(retries + 1):
        port = _free_port()
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", str(nproc), "--port", str(port), SCRIPT],
            env=env, capture_output=True, text=True, timeout=timeout)
        if proc.returncode == 0:
            out = {}
            for line in proc.stdout.splitlines():
                if line.startswith("DIST_LOSSES "):
                    rec = json.loads(line[len("DIST_LOSSES "):])
                    out[rec["rank"]] = rec["losses"]
            return out
        last = proc
    raise AssertionError(
        f"cluster failed\nSTDOUT:\n{last.stdout}\nSTDERR:\n{last.stderr}")


@pytest.mark.slow
def test_two_process_losses_match_single_rank():
    # single-rank oracle: the SAME script as a 1-process cluster (fresh
    # interpreter, like the reference's TestDistBase which subprocesses
    # both sides — keeps the oracle hermetic from suite-global state)
    ref = _run_cluster(1)[0]
    result = _run_cluster(2)
    assert sorted(result) == [0, 1], f"missing ranks: {result}"
    # both ranks see the same (replicated) loss
    np.testing.assert_allclose(result[0], result[1], rtol=1e-6)
    # distributed loss sequence == single-rank full-batch sequence
    np.testing.assert_allclose(result[0], ref, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_launcher_propagates_child_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--port", str(_free_port()), str(bad)],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 3
