"""Multi-process distributed tests — the TestDistBase analogue.

Reference: fluid/tests/unittests/test_dist_base.py:660 — spawn trainer
subprocesses with the PADDLE_TRAINER_* env contract on free local ports,
then assert their per-step losses match a single-rank run of the same model
on the full batch. Here the subprocesses bootstrap via the JAX coordination
service (init_parallel_env); the dp allreduce rides the compiled SPMD path
and the host-level collective/p2p surface (all_gather, reduce_scatter,
send/recv) is asserted from each rank's result file.

Results come back through per-rank JSON files (atomic rename), not stdout:
concurrent children interleave stdout lines through the launcher pipe,
which made line-parsing flake under load.
"""
import json
import os
import socket
import subprocess
import sys
import tempfile

import numpy as np
import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "dist_mp_model.py")


def _run_cluster(nproc: int, timeout=300, retries=2):
    """Retries on fresh ports (reference TestDistBase retries its cluster
    runs too — rendezvous can flake under parallel CI load)."""
    last = None
    for _ in range(retries + 1):
        with tempfile.TemporaryDirectory() as out_dir:
            env = dict(os.environ)
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            env["JAX_PLATFORMS"] = "cpu"
            env["DIST_OUT_DIR"] = out_dir
            env.pop("XLA_FLAGS", None)  # 1 local CPU device per process
            port = _free_port()
            try:
                proc = subprocess.run(
                    [sys.executable, "-m", "paddle_tpu.distributed.launch",
                     "--nproc_per_node", str(nproc), "--port", str(port),
                     SCRIPT],
                    env=env, capture_output=True, text=True,
                    timeout=timeout)
            except subprocess.TimeoutExpired as e:
                last = e
                continue
            out = {}
            for fn in os.listdir(out_dir):
                if fn.startswith("rank") and fn.endswith(".json"):
                    with open(os.path.join(out_dir, fn)) as f:
                        rec = json.load(f)
                    out[rec["rank"]] = rec
            if proc.returncode == 0 and len(out) == nproc:
                return out
            last = proc
    msg = (f"cluster failed\nSTDOUT:\n{last.stdout}\nSTDERR:\n{last.stderr}"
           if isinstance(last, subprocess.CompletedProcess)
           else f"cluster timed out: {last}")
    raise AssertionError(msg)


def _assert_cluster(nproc: int):
    ref = _run_cluster(1)[0]["losses"]
    result = _run_cluster(nproc)
    assert sorted(result) == list(range(nproc)), \
        f"missing ranks: {sorted(result)}"
    # every rank sees the same (replicated) loss sequence
    for r in range(1, nproc):
        np.testing.assert_allclose(result[0]["losses"],
                                   result[r]["losses"], rtol=1e-6)
    # distributed loss sequence == single-rank full-batch sequence
    np.testing.assert_allclose(result[0]["losses"], ref, rtol=1e-4,
                               atol=1e-6)
    # host-level collective surface (real cross-process exchanges)
    expect_gather = [[float(r), r + 0.5] for r in range(nproc)]
    for r in range(nproc):
        assert result[r]["all_gather"] == expect_gather, \
            (r, result[r]["all_gather"])
        if nproc > 1:
            # each rank contributed arange(w)+rank; chunk r of the sum is
            # w*r + sum(ranks)
            expect_rs = nproc * r + nproc * (nproc - 1) / 2
            np.testing.assert_allclose(result[r]["reduce_scatter"],
                                       [expect_rs])
            # ring: rank r hears from (r-1) % w
            assert result[r]["ring_recv"] == float((r - 1) % nproc)
            assert result[r]["ring_recv_bf16"] == float((r - 1) % nproc)


@pytest.mark.slow
def test_two_process_losses_match_single_rank():
    _assert_cluster(2)


@pytest.mark.slow
def test_four_process_losses_and_collectives():
    _assert_cluster(4)


@pytest.mark.slow
def test_launcher_propagates_child_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--port", str(_free_port()), str(bad)],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 3


@pytest.mark.slow
def test_fleet_metrics_match_single_rank():
    """Each rank evaluates half the data; fleet.metrics must equal the
    single-process metric over the full set (VERDICT r2 item 8,
    reference fleet/metrics/metric.py:1)."""
    script = os.path.join(REPO, "tests", "dist_fleet_metrics.py")

    def run(nproc):
        last = None
        for _ in range(3):
            with tempfile.TemporaryDirectory() as out_dir:
                env = dict(os.environ)
                env["PYTHONPATH"] = REPO + os.pathsep + env.get(
                    "PYTHONPATH", "")
                env["JAX_PLATFORMS"] = "cpu"
                env["DIST_OUT_DIR"] = out_dir
                env.pop("XLA_FLAGS", None)
                proc = subprocess.run(
                    [sys.executable, "-m",
                     "paddle_tpu.distributed.launch",
                     "--nproc_per_node", str(nproc),
                     "--port", str(_free_port()), script],
                    env=env, capture_output=True, text=True, timeout=240)
                recs = {}
                for fn in os.listdir(out_dir):
                    if fn.endswith(".json"):
                        with open(os.path.join(out_dir, fn)) as f:
                            rec = json.load(f)
                        recs[rec["rank"]] = rec
                if proc.returncode == 0 and len(recs) == nproc:
                    return recs
                last = proc
        raise AssertionError(f"metrics cluster failed:\n{last.stderr}")

    single = run(1)[0]
    dist = run(2)
    for metric in ("auc", "acc", "mae", "rmse", "sum"):
        # both ranks agree, and equal the single-rank full-set value
        np.testing.assert_allclose(dist[0][metric], dist[1][metric],
                                   rtol=1e-9, err_msg=metric)
        np.testing.assert_allclose(dist[0][metric], single[metric],
                                   rtol=1e-6, err_msg=metric)
