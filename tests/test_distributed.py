"""Distributed stack tests on the 8-device virtual CPU mesh.

Reference test analogues: unittests/test_dist_base.py:660 (asserts 2-rank
distributed losses ≈ single-rank losses — here the same assertion between
sharded-mesh and single-device runs), fleet meta-optimizer tests
(test_fleet_sharding_meta_optimizer.py — compile-time assertions, here
sharding-spec assertions), collective_*.py (op semantics inside shard_map),
pipeline_mnist.py (pp parity).
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
from paddle_tpu.parallel import (build_mesh, set_global_mesh,
                                 ShardedTrainStep, ShardingStage)
from paddle_tpu.parallel import mesh as mesh_mod

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    set_global_mesh(None)


def _tiny_cfg(**kw):
    return GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=2, max_seq_len=16, **kw)


def _data(batch=8):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randint(0, 64, (batch, 16))),
            paddle.to_tensor(rng.randint(0, 64, (batch, 16))))


def _run(mesh_kw, stage=0, steps=5, cfg_kw=None, batch=8):
    paddle.seed(0)
    mesh = build_mesh(**mesh_kw)
    set_global_mesh(mesh)
    model = GPT(_tiny_cfg(**(cfg_kw or {})))
    optim = opt.Adam(1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh,
                            sharding_stage=stage)
    x, y = _data(batch)
    return [float(step(x, y).numpy()) for _ in range(steps)]


def _single(steps=5, cfg_kw=None, batch=8):
    return _run(dict(dp=1, pp=1, tp=1, sp=1, sharding=1,
                     devices=jax.devices()[:1]), 0, steps, cfg_kw, batch)


def test_dp_matches_single_device():
    base = _single()
    dp = _run(dict(dp=8, pp=1, tp=1, sp=1, sharding=1))
    np.testing.assert_allclose(base, dp, rtol=2e-3, atol=2e-3)


def test_tp_matches_single_device():
    base = _single()
    tp = _run(dict(dp=1, pp=1, tp=8, sp=1, sharding=1))
    np.testing.assert_allclose(base, tp, rtol=2e-3, atol=2e-3)


def test_zero_stages_match_single_device():
    base = _single()
    for stage in (ShardingStage.OPTIMIZER, ShardingStage.GRADIENT,
                  ShardingStage.PARAMETER):
        z = _run(dict(dp=1, pp=1, tp=1, sp=1, sharding=8), stage)
        np.testing.assert_allclose(base, z, rtol=2e-3, atol=2e-3,
                                   err_msg=f"stage {stage}")


def test_hybrid_dp_tp_sharding():
    base = _single()
    hy = _run(dict(dp=2, pp=1, tp=2, sp=1, sharding=2),
              ShardingStage.GRADIENT)
    np.testing.assert_allclose(base, hy, rtol=2e-3, atol=2e-3)


def test_sequence_parallel():
    base = _single(cfg_kw=dict(sequence_parallel=True))
    sp = _run(dict(dp=2, pp=1, tp=2, sp=2, sharding=1),
              cfg_kw=dict(sequence_parallel=True))
    np.testing.assert_allclose(base, sp, rtol=2e-3, atol=2e-3)


def test_recompute_matches():
    base = _single()
    rc = _run(dict(dp=2, pp=1, tp=2, sp=1, sharding=2),
              cfg_kw=dict(use_recompute=True))
    np.testing.assert_allclose(base, rc, rtol=2e-3, atol=2e-3)


def test_pipeline_parity():
    from paddle_tpu.parallel.pipeline import (PipelinedGPT,
                                              pipelined_gpt_loss_fn)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=16)

    def run_pp(mesh_kw):
        paddle.seed(0)
        mesh = build_mesh(**mesh_kw)
        set_global_mesh(mesh)
        model = PipelinedGPT(cfg, mesh)
        optim = opt.Adam(1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, pipelined_gpt_loss_fn, optim,
                                mesh=mesh)
        x, y = _data(8)
        return [float(step(x, y).numpy()) for _ in range(5)]

    base = run_pp(dict(dp=1, pp=1, tp=1, sp=1, sharding=1,
                       devices=jax.devices()[:1]))
    pp = run_pp(dict(dp=2, pp=4, tp=1, sp=1, sharding=1))
    np.testing.assert_allclose(base, pp, rtol=3e-3, atol=3e-3)


def test_gradient_merge_matches_big_batch():
    paddle.seed(0)
    mesh = build_mesh(dp=1, pp=1, tp=1, sp=1, sharding=1,
                      devices=jax.devices()[:1])
    set_global_mesh(mesh)
    model = GPT(_tiny_cfg())
    optim = opt.SGD(0.1, parameters=model.parameters())
    step = ShardedTrainStep(model, gpt_loss_fn, optim, mesh=mesh,
                            grad_accum_steps=2)
    x, y = _data(8)
    xa, xb = x[:4], x[4:]
    ya, yb = y[:4], y[4:]
    step(xa, ya)
    w_before = model.parameters()[0].numpy().copy()
    # not applied yet after first micro-step? applied at 2nd
    step(xb, yb)
    w_after = model.parameters()[0].numpy()
    assert not np.allclose(w_before, w_after)

    # compare against single big-batch step
    paddle.seed(0)
    model2 = GPT(_tiny_cfg())
    optim2 = opt.SGD(0.1, parameters=model2.parameters())
    step2 = ShardedTrainStep(model2, gpt_loss_fn, optim2, mesh=mesh)
    step2(x, y)
    np.testing.assert_allclose(
        model.parameters()[0].numpy(), model2.parameters()[0].numpy(),
        rtol=2e-3, atol=2e-4)


def test_collectives_inside_shard_map():
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import shard_map
    import paddle_tpu.distributed as dist
    mesh = build_mesh(dp=8, pp=1, tp=1, sp=1, sharding=1)
    set_global_mesh(mesh)

    def body(x):
        t = paddle.Tensor(x)
        dist.all_reduce(t)
        return t._value

    # Full-manual shard_map: the pinned JAX rejects partial-manual
    # (axis_names={'dp'}) when out_specs refer to the manual axis of a
    # multi-axis mesh; with every axis manual the trivial (size-1 here)
    # axes are bound too and psum over 'dp' is well-defined.
    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)
    x = jnp.arange(8.0)
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full(8, x.sum()))

    def bcast(x):
        t = paddle.Tensor(x)
        dist.broadcast(t, src=3)
        return t._value
    f2 = shard_map(bcast, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   check_vma=False)
    np.testing.assert_allclose(np.asarray(f2(x)), np.full(8, 3.0))


def test_collectives_identity_outside_mesh():
    import paddle_tpu.distributed as dist
    t = paddle.to_tensor([1.0, 2.0])
    out = dist.all_reduce(t)
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])
    gathered = []
    dist.all_gather(gathered, t)
    assert len(gathered) == 1


def test_fleet_end_to_end():
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.sharding = True
    strategy.sharding_configs = {"sharding_degree": 2, "sharding_stage": 2}
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    assert fleet.worker_num() >= 1
    paddle.seed(0)
    model = GPT(_tiny_cfg())
    optim = opt.Adam(1e-3, parameters=model.parameters())
    step = fleet.distributed_train_step(model, gpt_loss_fn, optim)
    x, y = _data(8)
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_fleet_lamb_substitution():
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.optimizer import Lamb
    strategy = fleet.DistributedStrategy()
    strategy.lamb = True
    p = paddle.to_tensor([1.0], stop_gradient=False)
    p.trainable = True
    inner = opt.Adam(0.01, parameters=[p])
    fleet.init(is_collective=True, strategy=strategy)
    wrapped = fleet.distributed_optimizer(inner, strategy)
    assert isinstance(wrapped._inner, Lamb)


def test_distributed_batch_sampler_shards():
    from paddle_tpu.io import DistributedBatchSampler, TensorDataset
    ds = TensorDataset([paddle.arange(20).reshape([20, 1])])
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert not set(i0) & set(i1)
    assert len(i0) == len(i1) == 10


def test_tp_layer_specs():
    from paddle_tpu.distributed import (ColumnParallelLinear,
                                        RowParallelLinear,
                                        VocabParallelEmbedding)
    from paddle_tpu.parallel.api import param_spec
    col = ColumnParallelLinear(8, 16)
    row = RowParallelLinear(16, 8)
    emb = VocabParallelEmbedding(32, 8)
    assert param_spec(col.weight) == (None, "tp")
    assert param_spec(row.weight) == ("tp", None)
    assert param_spec(emb.weight) == ("tp", None)
    # runs unsharded too
    x = paddle.randn([2, 8])
    assert row(col(x)).shape == [2, 8]


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    __graft_entry__.dryrun_multichip(8)


def test_pipeline_layer_generic_parity_pp4_micro16():
    """Generic PipelineLayer (pp=4, num_micro=16) must match running the
    same blocks sequentially on one device (VERDICT r2 item 4)."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import nn
    from paddle_tpu.parallel import build_mesh, set_global_mesh, \
        ShardedTrainStep
    from paddle_tpu.parallel.pipeline import PipelineLayer

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 16)
            self.ln = nn.LayerNorm(16)

        def forward(self, x):
            return self.ln(x + self.fc2(paddle.tanh(self.fc1(x))))

    paddle.seed(7)
    blocks = [Block() for _ in range(4)]
    x = np.random.RandomState(0).randn(16, 3, 16).astype(np.float32)

    # sequential oracle on plain eager
    ref = paddle.to_tensor(x)
    for b in blocks:
        ref = b(ref)
    ref = ref.numpy()

    mesh = build_mesh(dp=1, pp=4, tp=1, sp=1, sharding=1,
                      devices=jax.devices()[:4])
    set_global_mesh(mesh)
    pipe = PipelineLayer(blocks, mesh=mesh, num_micro=16)
    out = pipe(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)

    # and it trains through ShardedTrainStep (loss decreases)
    y = np.random.RandomState(1).randn(16, 3, 16).astype(np.float32)
    optim = opt.AdamW(1e-2, parameters=pipe.parameters())
    step = ShardedTrainStep(
        pipe, lambda m, a, b: ((m(a) - b) ** 2).mean(), optim, mesh=mesh)
    l0 = float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
    for _ in range(4):
        l1 = float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
    assert l1 < l0


def test_pipeline_remat_bounds_activation_memory():
    """Residuals stored for the pipeline backward must be bounded by the
    inter-stage carries, not scale with the per-layer internals x
    num_micro: with remat, growing num_micro 4 -> 16 at FIXED global batch
    must not grow saved-residual bytes materially, and the remat build
    must store far less than the no-remat build (reference analogue:
    SectionWorker's per-microbatch scopes, section_worker.cc:34-105)."""
    import jax
    import jax.numpy as jnp
    from jax._src.ad_checkpoint import saved_residuals
    import paddle_tpu as paddle
    from paddle_tpu.parallel import build_mesh, set_global_mesh
    from paddle_tpu.parallel.pipeline import pipeline_spmd

    mesh = build_mesh(dp=1, pp=4, tp=1, sp=1, sharding=1,
                      devices=jax.devices()[:4])
    set_global_mesh(mesh)

    H, inner = 16, 64

    def stage(p, x):
        # 1 matmul up, gelu, matmul down: internals (x@w1 pre-gelu) are
        # the memory hogs a pipeline must NOT store per microbatch
        h = jax.nn.gelu(x @ p["w1"])
        return x + h @ p["w2"]

    rs = np.random.RandomState(0)
    stacked = {"w1": jnp.asarray(rs.randn(4, H, inner), jnp.float32),
               "w2": jnp.asarray(rs.randn(4, inner, H), jnp.float32)}
    GLOBAL = 32

    def residual_bytes(num_micro, remat):
        fn = pipeline_spmd(stage, mesh, 4, num_micro, remat_stages=remat)
        xs = jnp.zeros((num_micro, GLOBAL // num_micro, H), jnp.float32)

        def loss(params):
            return jnp.sum(fn(params, xs) ** 2)
        res = saved_residuals(loss, stacked)
        return sum(int(np.prod(aval.shape)) * aval.dtype.itemsize
                   for aval, _ in res)

    remat_4 = residual_bytes(4, True)
    remat_16 = residual_bytes(16, True)
    plain_16 = residual_bytes(16, False)
    # bounded in num_micro (fixed global batch): within 2x across 4 -> 16
    assert remat_16 < 2 * remat_4, (remat_4, remat_16)
    # and materially below the store-everything build
    assert remat_16 < plain_16 / 2, (remat_16, plain_16)
