"""FD-gradient suite for the differentiable op tail.

Round-5 companion to tests/test_op_suite.py: the ops here already have
forward value coverage elsewhere (test_op_suite / test_op_tail / test_nn),
but no finite-difference gradient check. Each case seeds a random cotangent
on the output and compares the eager-tape gradient against float64 central
differences — the reference's OpTest.check_grad contract
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:1329).

tests/test_grad_coverage.py audits GRAD_CASES mechanically: every case
must declare `grad` and `op_types`, and the FD-grad-checked op set must
not silently shrink below its recorded floor.

Kink discipline: inputs are placed away from non-smooth points (clip bounds,
hinge margins, max ties — order-statistics ops draw from a shuffled linspace
so neighbouring values differ by far more than the FD step).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.testing import OpTestCase, run_case

rng = np.random.RandomState(11)


def r(*shape, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


def rpos(*shape, lo=0.3, hi=2.0):
    return rng.uniform(lo, hi, size=shape).astype("float32")


def rsep(*shape, lo=-2.0, hi=2.0):
    """Well-separated values (shuffled linspace): safe for order-statistics
    ops under a 1e-5 FD step."""
    n = int(np.prod(shape))
    v = np.linspace(lo, hi, n)
    rng.shuffle(v)
    return v.reshape(shape).astype("float32")


def spd(n):
    """Symmetric positive-definite matrix (well-conditioned)."""
    a = rng.uniform(-1, 1, size=(n, n))
    return (a @ a.T + n * np.eye(n)).astype("float32")


C = OpTestCase

# ---------------------------------------------------------------- manip
MANIP = [
    C(lambda a, b: paddle.concat([a, b], axis=0), (r(2, 3), r(1, 3)),
      grad=(0, 1), op_types=["concat"], name="concat"),
    C(lambda a, b: paddle.stack([a, b], axis=1), (r(2, 3), r(2, 3)),
      grad=(0, 1), op_types=["stack"], name="stack"),
    C(lambda x: paddle.unstack(x, axis=0)[1], (r(3, 2, 2),),
      grad=(0,), op_types=["unstack"], name="unstack"),
    C(lambda x: paddle.split(x, 2, axis=1)[0], (r(2, 4),),
      grad=(0,), op_types=["split"], name="split"),
    C(lambda x: paddle.squeeze(x, axis=1), (r(3, 1, 2),),
      grad=(0,), op_types=["squeeze", "squeeze2"], name="squeeze"),
    C(lambda x: paddle.unsqueeze(x, axis=1), (r(3, 2),),
      grad=(0,), op_types=["unsqueeze", "unsqueeze2"], name="unsqueeze"),
    C(lambda x: paddle.flatten(x, start_axis=1), (r(2, 2, 3),),
      grad=(0,),
      op_types=["flatten", "flatten2", "flatten_contiguous_range"],
      name="flatten"),
    C(lambda x: paddle.flip(x, axis=[0, 1]), (r(2, 3),),
      grad=(0,), op_types=["flip", "reverse"], name="flip"),
    C(lambda x: paddle.roll(x, shifts=2, axis=1), (r(2, 4),),
      grad=(0,), op_types=["roll"], name="roll"),
    C(lambda x: paddle.rot90(x, k=1, axes=[0, 1]), (r(2, 3),),
      grad=(0,), op_types=["rot90"], name="rot90"),
    C(lambda x: paddle.moveaxis(x, 0, 2), (r(2, 2, 3),),
      grad=(0,), op_types=["moveaxis"], name="moveaxis"),
    C(lambda x: paddle.triu(x, diagonal=0), (r(3, 3),),
      grad=(0,), op_types=["triu"], name="triu"),
    C(lambda x: paddle.diag(x, offset=1), (r(3, 3),),
      grad=(0,), op_types=["diag"], name="diag_extract"),
    C(lambda x: paddle.diagflat(x), (r(4),),
      grad=(0,), op_types=["diagflat"], name="diagflat"),
    C(lambda x: paddle.diagonal(x, axis1=0, axis2=1), (r(3, 3),),
      grad=(0,), op_types=["diagonal"], name="diagonal"),
    C(lambda x: paddle.repeat_interleave(x, 2, axis=0), (r(2, 3),),
      grad=(0,), op_types=["repeat_interleave"], name="repeat_interleave"),
    C(lambda x, m: paddle.masked_select(x, m),
      (r(2, 3), np.array([[True, False, True], [False, True, True]])),
      grad=(0,), op_types=["masked_select"], name="masked_select"),
    C(lambda x, i: paddle.index_sample(x, i),
      (r(2, 4), np.array([[0, 2], [1, 3]], dtype=np.int64)),
      grad=(0,), op_types=["index_sample"], name="index_sample"),
    C(lambda x, i, v: paddle.index_add(x, i, 0, v),
      (r(3, 2), np.array([0, 2], dtype=np.int64), r(2, 2)),
      grad=(0, 2), op_types=["index_add"], name="index_add"),
    C(lambda x, i, v: paddle.put_along_axis(x, i, v, axis=1,
                                            reduce="add"),
      (r(2, 3), np.array([[0], [2]], dtype=np.int64), r(2, 1)),
      grad=(0, 2), op_types=["put_along_axis"], name="put_along_axis"),
    C(lambda x, y: paddle.lerp(x, y, 0.3), (r(2, 3), r(2, 3)),
      grad=(0, 1), op_types=["lerp"], name="lerp"),
    # values well inside (min,max): clip is identity there, kink-safe
    C(lambda x: paddle.clip(x, min=-5.0, max=5.0), (r(2, 3),),
      grad=(0,), op_types=["clip"], name="clip"),
    C(lambda a, b, c: paddle.add_n([a, b, c]),
      (r(2, 2), r(2, 2), r(2, 2)),
      grad=(0, 1, 2), op_types=["add_n", "sum"], name="add_n"),
    C(lambda x: F.pad(x, [1, 1, 0, 1], mode="constant", value=0.0),
      (r(1, 1, 2, 3),), grad=(0,),
      op_types=["pad", "pad2d", "pad3d", "pad_constant_like"],
      name="pad_constant"),
    C(lambda x: F.pad(x, [1, 1, 1, 1], mode="reflect"),
      (r(1, 1, 3, 3),), grad=(0,), op_types=["pad2d"], name="pad_reflect"),
    C(lambda x: paddle.assign(x), (r(2, 3),),
      grad=(0,), op_types=["assign"], name="assign"),
]

# ---------------------------------------------------------------- linalg
LINALG = [
    C(paddle.bmm, (r(2, 2, 3), r(2, 3, 2)), grad=(0, 1),
      op_types=["bmm"], name="bmm"),
    C(lambda x, y: paddle.tensordot(x, y, axes=2),
      (r(2, 3, 2), r(3, 2, 4)), grad=(0, 1),
      op_types=["tensordot"], name="tensordot"),
    C(lambda a, b: paddle.einsum("ij,jk->ik", a, b), (r(2, 3), r(3, 2)),
      grad=(0, 1), op_types=["einsum"], name="einsum"),
    C(lambda a, b, c: paddle.linalg.multi_dot([a, b, c]),
      (r(2, 3), r(3, 4), r(4, 2)), grad=(0, 1, 2),
      op_types=["multi_dot", "mul"], name="multi_dot"),
    C(lambda x: paddle.linalg.cholesky(x), (spd(3),),
      grad=(0,), op_types=["cholesky"], name="cholesky",
      grad_atol=5e-3),
    C(lambda x: paddle.linalg.det(x), (spd(3),),
      grad=(0,), op_types=["det"], name="det"),
    # slogdet returns stacked [sign, logabs]; SPD input keeps sign
    # constant (+1) so its FD and analytic contributions are both zero
    C(lambda x: paddle.linalg.slogdet(x), (spd(3),),
      grad=(0,), op_types=["slogdet"], name="slogdet"),
    C(lambda x: paddle.linalg.inverse(x), (spd(3),),
      grad=(0,), op_types=["inverse"], name="inverse"),
    C(lambda x: paddle.linalg.matrix_power(x, 3), (spd(2),),
      grad=(0,), op_types=["matrix_power"], name="matrix_power"),
    C(lambda a, b: paddle.linalg.solve(a, b), (spd(3), r(3, 2)),
      grad=(0, 1), op_types=["solve"], name="solve"),
    C(lambda a, b: paddle.linalg.triangular_solve(a, b, upper=False),
      (np.tril(spd(3)), r(3, 2)), grad=(0, 1),
      op_types=["triangular_solve"], name="triangular_solve"),
    C(lambda a, b: paddle.linalg.cholesky_solve(b, np.linalg.cholesky(
        spd_fixed).astype("float32"), upper=False),
      (spd(3), r(3, 2)), grad=(1,),
      op_types=["cholesky_solve"], name="cholesky_solve"),
    C(lambda x: paddle.linalg.pinv(x), (r(3, 2),),
      grad=(0,), op_types=["pinv"], name="pinv", grad_atol=5e-3),
    # gauge-free outputs only: singular values / eigenvalues
    C(lambda x: paddle.linalg.svd(x)[1], (r(3, 2),),
      grad=(0,), op_types=["svd"], name="svd_singular_values"),
    C(lambda x: paddle.linalg.eigh(x)[0], (spd(3),),
      grad=(0,), op_types=["eigh"], name="eigh_eigenvalues"),
    C(lambda x: paddle.linalg.norm(x, p=2), (r(2, 3),),
      grad=(0,), op_types=["norm", "p_norm", "frobenius_norm"],
      name="norm_fro"),
    C(lambda x: paddle.linalg.norm(x, p=3, axis=1), (rpos(2, 3),),
      grad=(0,), op_types=["p_norm"], name="p_norm3"),
    C(lambda x: F.normalize(x, p=2, axis=1), (r(2, 3),),
      grad=(0,), op_types=["normalize_l2"], name="normalize"),
    C(lambda x: paddle.trace(x), (r(3, 3),),
      grad=(0,), op_types=["trace"], name="trace"),
    C(lambda x, y: paddle.linalg.cov(paddle.stack([x, y])),
      (r(4), r(4)), grad=(0, 1), op_types=["cov"], name="cov"),
]
spd_fixed = spd(3)

# ------------------------------------------------------- elementwise tail
ELEM = [
    C(lambda x, y: paddle.copysign(x, y), (rpos(2, 3), r(2, 3)),
      grad=(0,), op_types=["copysign"], name="copysign"),
    C(lambda x, y: paddle.divide_no_nan(x, y), (r(2, 3), rpos(2, 3)),
      grad=(0, 1), op_types=["divide_no_nan"], name="divide_no_nan"),
    # disjoint linspace grids: no cross-array ties for the max/min pick
    C(lambda x, y: paddle.fmax(x, y),
      (rsep(2, 3), rsep(2, 3, lo=-1.93, hi=1.87)),
      grad=(0, 1), op_types=["elementwise_fmax"], name="fmax"),
    C(lambda x, y: paddle.fmin(x, y),
      (rsep(2, 3), rsep(2, 3, lo=-1.93, hi=1.87)),
      grad=(0, 1), op_types=["elementwise_fmin"], name="fmin"),
    C(lambda x, y: paddle.hypot(x, y), (rpos(2, 3), rpos(2, 3)),
      grad=(0, 1), op_types=["hypot"], name="hypot"),
    C(lambda x: paddle.ldexp(x, paddle.to_tensor(
        np.array([1, 2, 0], dtype=np.int32))), (r(2, 3),),
      grad=(0,), op_types=["ldexp"], name="ldexp"),
    # fractional inputs well away from integers: frac is identity-shift
    C(lambda x: paddle.frac(x), (r(2, 3, lo=0.2, hi=0.8),),
      grad=(0,), op_types=["frac"], name="frac"),
    C(lambda x: paddle.nan_to_num(x), (r(2, 3),),
      grad=(0,), op_types=["nan_to_num"], name="nan_to_num"),
    C(lambda x: paddle.logit(x), (r(2, 3, lo=0.2, hi=0.8),),
      grad=(0,), op_types=["logit"], name="logit"),
    C(lambda x: paddle.cummax(x, axis=1)[0], (rsep(2, 6),),
      grad=(0,), op_types=["cummax"], name="cummax"),
    C(lambda x: paddle.logcumsumexp(x, axis=1), (r(2, 4),),
      grad=(0,), op_types=["logcumsumexp"], name="logcumsumexp"),
    C(lambda x: paddle.quantile(x, 0.37, axis=1), (rsep(2, 8),),
      grad=(0,), op_types=["quantile"], name="quantile"),
    C(lambda x: paddle.median(x, axis=1), (rsep(2, 7),),
      grad=(0,), op_types=["median"], name="median"),
    C(lambda x: paddle.kthvalue(x, k=2, axis=1)[0], (rsep(2, 5),),
      grad=(0,), op_types=["kthvalue"], name="kthvalue"),
    C(lambda x: paddle.mode(x, axis=1)[0], (rsep(2, 5),),
      grad=(0,), op_types=["mode"], name="mode"),
    C(lambda x: paddle.diff(x, axis=1), (r(2, 5),),
      grad=(0,), op_types=["diff"], name="diff"),
    C(lambda x: paddle.trapezoid(x, dx=0.5, axis=1), (r(2, 5),),
      grad=(0,), op_types=["trapezoid", "cumulative_trapezoid"],
      name="trapezoid"),
    C(lambda x: paddle.renorm(x, p=2.0, axis=0, max_norm=100.0),
      (r(3, 4),), grad=(0,), op_types=["renorm"], name="renorm"),
    C(lambda x: paddle.angle(x.astype("complex64")), (r(2, 3),),
      grad=(), op_types=["angle"], name="angle_smoke"),
]

# ------------------------------------------------------------ activations
ACT = [
    C(lambda x: F.celu(x, alpha=1.2), (r(2, 3),),
      grad=(0,), op_types=["celu"], name="celu"),
    C(lambda x: F.selu(x), (r(2, 3),),
      grad=(0,), op_types=["selu"], name="selu"),
    C(lambda x: F.mish(x), (r(2, 3),),
      grad=(0,), op_types=["mish"], name="mish"),
    # kink-safe bands: relu6 kinks at {0,6}; hard_* kink per formula
    C(lambda x: F.relu6(x), (rsep(2, 4, lo=0.5, hi=5.5),),
      grad=(0,), op_types=["relu6"], name="relu6"),
    C(lambda x: F.hardsigmoid(x), (r(2, 3, lo=-2.5, hi=2.5),),
      grad=(0,), op_types=["hard_sigmoid"], name="hardsigmoid"),
    C(lambda x: F.hardswish(x), (r(2, 3, lo=-2.5, hi=2.5),),
      grad=(0,), op_types=["hard_swish"], name="hardswish"),
    C(lambda x: F.hardtanh(x, min=-1.0, max=1.0), (r(2, 3, lo=-.9, hi=.9),),
      grad=(0,), op_types=["hard_tanh"], name="hardtanh"),
    C(lambda x: F.hardshrink(x, threshold=0.5),
      (rsep(2, 4, lo=0.6, hi=1.8),),
      grad=(0,), op_types=["hard_shrink"], name="hardshrink"),
    C(lambda x: F.softshrink(x, threshold=0.3),
      (rsep(2, 4, lo=0.5, hi=1.8),),
      grad=(0,), op_types=["softshrink"], name="softshrink"),
    C(lambda x: F.softsign(x), (r(2, 3),),
      grad=(0,), op_types=["softsign"], name="softsign"),
    C(lambda x: F.tanhshrink(x), (r(2, 3),),
      grad=(0,), op_types=["tanh_shrink"], name="tanhshrink"),
    C(lambda x: F.thresholded_relu(x, threshold=0.4),
      (rsep(2, 4, lo=0.6, hi=1.9),),
      grad=(0,), op_types=["thresholded_relu"], name="thresholded_relu"),
    C(lambda x: paddle.stanh(x, scale_a=0.7, scale_b=1.7), (r(2, 3),),
      grad=(0,), op_types=["stanh"], name="stanh"),
    C(lambda x: F.maxout(x, groups=2, axis=1), (rsep(1, 4, 2, 2),),
      grad=(0,), op_types=["maxout"], name="maxout"),
    C(lambda x: F.glu(x, axis=1), (r(2, 4),),
      grad=(0,), op_types=["glu"], name="glu"),
    C(lambda x, w: F.prelu(x, w), (r(1, 2, 3), rpos(2)),
      grad=(0, 1), op_types=["prelu"], name="prelu"),
    C(lambda x: F.label_smooth(x, epsilon=0.1), (rpos(2, 4),),
      grad=(0,), op_types=["label_smooth"], name="label_smooth"),
]

# ---------------------------------------------------------------- losses
_away = rng.uniform(-2, 2, (2, 3)).astype("float32")
LOSS = [
    C(lambda x, y: F.l1_loss(x, y), (r(2, 3), r(2, 3, lo=2.5, hi=4.0)),
      grad=(0, 1), op_types=["l1_loss"], name="l1_loss"),
    # |x-y| far from the delta=1 boundary on every element
    C(lambda x, y: F.smooth_l1_loss(x, y, delta=1.0),
      (r(2, 3, lo=-0.1, hi=0.1), r(2, 3, lo=2.0, hi=3.0)),
      grad=(0, 1), op_types=["smooth_l1_loss", "huber_loss"],
      name="smooth_l1_far"),
    C(lambda x, y: F.smooth_l1_loss(x, y, delta=10.0),
      (r(2, 3), r(2, 3)),
      grad=(0, 1), op_types=["huber_loss"], name="huber_quadratic"),
    C(lambda x, t: F.kl_div(paddle.log(x), t, reduction="mean"),
      (rpos(2, 4), rpos(2, 4)),
      grad=(0, 1), op_types=["kl_div", "kldiv_loss"], name="kl_div"),
    C(lambda p, y: F.log_loss(p, y),
      (r(2, 1, lo=0.2, hi=0.8), np.array([[1.0], [0.0]],
                                         dtype=np.float32)),
      grad=(0,), op_types=["log_loss"], name="log_loss"),
    C(lambda a, b, y: F.margin_ranking_loss(a, b, y, margin=0.5),
      (r(2, 3, lo=1.0, hi=2.0), r(2, 3, lo=-2.0, hi=-1.0),
       np.ones((2, 3), dtype=np.float32)),
      grad=(0, 1), op_types=["margin_ranking_loss", "margin_rank_loss",
                             "rank_loss"],
      name="margin_ranking_active"),
    C(lambda x, y: F.cosine_embedding_loss(
        x, y, paddle.to_tensor(np.array([1, 1], dtype=np.int64))),
      (r(2, 4), r(2, 4)),
      grad=(0, 1), op_types=["cosine_embedding_loss"],
      name="cosine_embedding"),
    C(lambda x, y: F.hinge_embedding_loss(x, y, margin=5.0),
      (rpos(2, 3), np.sign(_away).astype(np.float32)),
      grad=(0,), op_types=["hinge_embedding_loss"],
      name="hinge_embedding"),
    C(lambda a, p, n: F.triplet_margin_loss(a, p, n, margin=8.0),
      (r(2, 4), r(2, 4), r(2, 4)),
      grad=(0, 1, 2), op_types=["triplet_margin_loss"],
      name="triplet_margin_active"),
    C(lambda x, t: F.nll_loss(F.log_softmax(x, axis=1), t),
      (r(3, 4), np.array([0, 2, 1], dtype=np.int64)),
      grad=(0,), op_types=["nll_loss"], name="nll_loss"),
    C(lambda x, t: F.binary_cross_entropy_with_logits(x, t),
      (r(2, 3), rng.uniform(0.1, 0.9, (2, 3)).astype("float32")),
      grad=(0, 1), op_types=["sigmoid_cross_entropy_with_logits"],
      name="bce_with_logits"),
    C(lambda x, y: F.cosine_similarity(x, y, axis=1), (r(2, 4), r(2, 4)),
      grad=(0, 1), op_types=["cosine_similarity"], name="cosine_sim"),
    C(lambda x, y, w: F.bilinear(x, y, w),
      (r(2, 3), r(2, 4), r(2, 3, 4)),
      grad=(0, 1, 2), op_types=["bilinear", "bilinear_tensor_product"],
      name="bilinear"),
]

# ------------------------------------------------------------- nn kernels
NN = [
    C(lambda x, w: F.conv2d_transpose(x, w, stride=2, padding=0),
      (r(1, 2, 3, 3), r(2, 2, 2, 2)),
      grad=(0, 1), op_types=["conv2d_transpose",
                             "depthwise_conv2d_transpose"],
      name="conv2d_transpose"),
    C(lambda x, w: F.conv3d(x, w, padding=1),
      (r(1, 2, 3, 3, 3), r(2, 2, 2, 2, 2)),
      grad=(0, 1), op_types=["conv3d"], name="conv3d"),
    C(lambda x, w: F.conv3d_transpose(x, w, stride=1),
      (r(1, 2, 2, 2, 2), r(2, 2, 2, 2, 2)),
      grad=(0, 1), op_types=["conv3d_transpose"], name="conv3d_transpose"),
    C(lambda x: F.avg_pool2d(x, kernel_size=2, stride=1),
      (r(1, 1, 3, 3),),
      grad=(0,), op_types=["pool_avg"], name="avg_pool2d"),
    C(lambda x: F.max_pool2d(x, kernel_size=2, stride=1),
      (rsep(1, 1, 3, 3),),
      grad=(0,), op_types=["pool_max"], name="max_pool2d"),
    C(lambda x: F.max_pool2d(x, kernel_size=2, return_mask=True)[0],
      (rsep(1, 1, 4, 4),),
      grad=(0,), op_types=["max_pool2d_with_index",
                           "max_pool3d_with_index"],
      name="max_pool2d_with_index"),
    C(lambda x: F.adaptive_avg_pool2d(x, output_size=2),
      (r(1, 1, 4, 4),),
      grad=(0,), op_types=["adaptive_pool"], name="adaptive_avg_pool2d"),
    C(lambda x: F.interpolate(x, scale_factor=2, mode="bilinear",
                              align_corners=False),
      (r(1, 1, 3, 3),), grad=(0,),
      op_types=["interpolate", "bilinear_interp", "bilinear_interp_v2",
                "linear_interp", "linear_interp_v2"],
      name="interp_bilinear"),
    C(lambda x: F.interpolate(x, scale_factor=2, mode="bicubic"),
      (r(1, 1, 3, 3),), grad=(0,),
      op_types=["bicubic_interp", "bicubic_interp_v2"],
      name="interp_bicubic"),
    C(lambda x: F.interpolate(x, scale_factor=2, mode="trilinear",
                              data_format="NCDHW"),
      (r(1, 1, 2, 2, 2),), grad=(0,),
      op_types=["trilinear_interp", "trilinear_interp_v2"],
      name="interp_trilinear"),
    C(lambda x, g: F.grid_sample(x, g, align_corners=False),
      (r(1, 1, 3, 3), r(1, 2, 2, 2, lo=-0.7, hi=0.7)),
      grad=(0, 1), op_types=["grid_sampler"], name="grid_sample"),
    C(lambda x: F.pixel_shuffle(x, 2), (r(1, 4, 2, 2),),
      grad=(0,), op_types=["pixel_shuffle"], name="pixel_shuffle"),
    C(lambda x: F.unfold(x, kernel_sizes=2), (r(1, 2, 3, 3),),
      grad=(0,), op_types=["unfold"], name="unfold"),
    C(lambda x: F.fold(x, output_sizes=3, kernel_sizes=2),
      (r(1, 8, 4),),
      grad=(0,), op_types=["fold"], name="fold"),
    C(lambda x: F.local_response_norm(x, size=3), (r(1, 4, 2, 2),),
      grad=(0,), op_types=["local_response_norm", "lrn"], name="lrn"),
    C(lambda x, w, b: F.group_norm(x, num_groups=2, weight=w, bias=b),
      (r(1, 4, 2, 2), r(4), r(4)),
      grad=(0, 1, 2), op_types=["group_norm"], name="group_norm"),
    C(lambda x, w, b: F.instance_norm(x, weight=w, bias=b),
      (r(2, 2, 3, 3), r(2), r(2)),
      grad=(0, 1, 2), op_types=["instance_norm"], name="instance_norm"),
    C(lambda x, i: F.embedding(i, x),
      (r(5, 3), np.array([[0, 2], [4, 1]], dtype=np.int64)),
      grad=(0,), op_types=["lookup_table", "lookup_table_v2"],
      name="embedding_weight_grad"),
]

# ---------------------------------------------------- tail ops (wave 2)
from paddle_tpu.ops import extra_ops, sequence_ops  # noqa: E402
from paddle_tpu.ops.vision_ops import shuffle_channel  # noqa: E402
import paddle_tpu.nn as pnn  # noqa: E402

# module-level cells: weights fixed across the FD sweep; f64 params so
# the lax.scan carry dtype matches the harness's float64 inputs
_lstm_cell = pnn.LSTMCell(3, 4)
_gru_cell = pnn.GRUCell(3, 4)
_rnn_cell = pnn.SimpleRNNCell(3, 4)
_lstm_net = pnn.LSTM(2, 3, 1)
_gru_net = pnn.GRU(2, 3, 1)
_srnn_net = pnn.SimpleRNN(2, 3, 1)
import jax.numpy as _jnp  # noqa: E402
for _net in (_lstm_net, _gru_net, _srnn_net):
    for _p in _net.parameters():
        _p._value = _jnp.asarray(_p.numpy().astype(np.float64))

_seg_ids = np.array([0, 0, 1, 2, 2], dtype=np.int64)
_seq_len = np.array([3, 2], dtype=np.int64)

TAIL2 = [
    C(lambda x, y: paddle.meshgrid(x, y)[0], (r(3), r(2)),
      grad=(0,), op_types=["meshgrid"], name="meshgrid"),
    C(lambda a, b, i: paddle.multiplex([a, b], i),
      (r(3, 2), r(3, 2), np.array([0, 1, 0], dtype=np.int64)),
      grad=(0, 1), op_types=["multiplex"], name="multiplex"),
    C(lambda x: paddle.unbind(x, axis=1)[1], (r(2, 3),),
      grad=(0,), op_types=["unbind"], name="unbind"),
    C(lambda x: paddle.crop(x, shape=[2, 2], offsets=[0, 1]), (r(3, 4),),
      grad=(0,), op_types=["crop_tensor"], name="crop"),
    C(lambda a, b: paddle.broadcast_tensors([a, b])[0],
      (r(1, 3), r(2, 1)),
      grad=(0,), op_types=["broadcast_tensors"], name="broadcast_tensors"),
    C(lambda x: paddle.vander(x, n=4), (r(3),),
      grad=(0,), op_types=["vander"], name="vander"),
    C(lambda x, i: paddle.take(x, i),
      (r(2, 4), np.array([0, 5, 3], dtype=np.int64)),
      grad=(0,), op_types=["take"], name="take"),
    C(lambda x, i, u: paddle.scatter_nd_add(x, i, u),
      (r(3, 2), np.array([[0], [2]], dtype=np.int64), r(2, 2)),
      grad=(0, 2), op_types=["scatter_nd_add"], name="scatter_nd_add"),
    # losses / misc (extra_ops module surface; fluid-era kernels)
    C(lambda p, l: extra_ops.hinge_loss(p, l),
      (r(3, 1, lo=-0.5, hi=0.5),
       np.array([[1.0], [0.0], [1.0]], dtype=np.float32)),
      grad=(0,), op_types=["hinge_loss"], name="hinge_loss_active"),
    C(lambda p, l: extra_ops.modified_huber_loss(p, l),
      (r(3, 1, lo=-0.4, hi=0.4),
       np.array([[1.0], [0.0], [1.0]], dtype=np.float32)),
      grad=(0,), op_types=["modified_huber_loss"],
      name="modified_huber_quadratic"),
    C(lambda p, l: extra_ops.teacher_student_sigmoid_loss(p, l),
      (r(3, 1), np.array([[0.3], [0.8], [0.1]], dtype=np.float32)),
      grad=(0,), op_types=["teacher_student_sigmoid_loss"],
      name="teacher_student"),
    C(lambda x, l: extra_ops.bpr_loss(x, l),
      (r(2, 4), np.array([[1], [3]], dtype=np.int64)),
      grad=(0,), op_types=["bpr_loss"], name="bpr_loss"),
    C(lambda x, y: extra_ops.cos_sim(x, y), (r(2, 4), r(2, 4)),
      grad=(0, 1), op_types=["cos_sim"], name="cos_sim"),
    C(lambda x: extra_ops.squared_l2_norm(x), (r(2, 3),),
      grad=(0,), op_types=["squared_l2_norm"], name="squared_l2_norm"),
    C(lambda x: extra_ops.l1_norm(x), (rsep(2, 4, lo=0.3, hi=1.9),),
      grad=(0,), op_types=["l1_norm"], name="l1_norm_positive"),
    C(lambda x: extra_ops.space_to_depth(x, 2), (r(1, 1, 4, 4),),
      grad=(0,), op_types=["space_to_depth"], name="space_to_depth"),
    C(lambda x: shuffle_channel(x, 2), (r(1, 4, 2, 2),),
      grad=(0,), op_types=["shuffle_channel"], name="shuffle_channel"),
    C(lambda x: F.pixel_unshuffle(x, 2), (r(1, 1, 4, 4),),
      grad=(0,), op_types=["pixel_unshuffle"], name="pixel_unshuffle"),
    C(lambda x, y: extra_ops.fsp_matrix(x, y),
      (r(1, 2, 3, 3), r(1, 3, 3, 3)),
      grad=(0, 1), op_types=["fsp"], name="fsp_matrix"),
    C(lambda x, w: extra_ops.row_conv(x, w), (r(1, 4, 3), r(2, 3)),
      grad=(0, 1), op_types=["row_conv"], name="row_conv"),
    C(lambda x, y: extra_ops.conv_shift(x, y), (r(2, 5), r(2, 3)),
      grad=(0, 1), op_types=["conv_shift"], name="conv_shift"),
    C(lambda e, t, l, ln: extra_ops.linear_chain_crf(e, t, l, ln),
      (r(2, 3, 4), r(6, 4),
       np.array([[0, 2, 1], [3, 1, 0]], dtype=np.int64),
       np.array([3, 2], dtype=np.int64)),
      grad=(0, 1), op_types=["linear_chain_crf"], name="linear_chain_crf"),
    # segments (well-separated data for the max/min switch points)
    C(lambda x, i: extra_ops.segment_sum(x, i), (r(5, 2), _seg_ids),
      grad=(0,), op_types=["segment_pool_sum"], name="segment_sum"),
    C(lambda x, i: extra_ops.segment_max(x, i), (rsep(5, 2), _seg_ids),
      grad=(0,), op_types=["segment_pool_max"], name="segment_max"),
    C(lambda x, i: extra_ops.segment_min(x, i), (rsep(5, 2), _seg_ids),
      grad=(0,), op_types=["segment_pool_min"], name="segment_min"),
    # ragged (dense + lengths) sequence ops
    C(lambda x, ln: sequence_ops.sequence_pool(x, ln, "mean"),
      (r(2, 3, 2), _seq_len),
      grad=(0,), op_types=["sequence_pool"], name="sequence_pool_mean"),
    C(lambda x, ln: sequence_ops.sequence_softmax(x, ln),
      (r(2, 4), _seq_len),
      grad=(0,), op_types=["sequence_softmax"], name="sequence_softmax"),
    C(lambda x, ln: sequence_ops.sequence_pad(x, ln, maxlen=3)[0],
      (r(5, 2), _seq_len),
      grad=(0,), op_types=["sequence_pad"], name="sequence_pad"),
    C(lambda x, ln: sequence_ops.sequence_reverse(x, ln),
      (r(2, 3, 2), _seq_len),
      grad=(0,), op_types=["sequence_reverse"], name="sequence_reverse"),
    # nn: norms / attention / ctc / focal / unpool / rois
    C(lambda x, m, v, w, b: F.batch_norm(x, m, v, weight=w, bias=b,
                                         training=True),
      (r(3, 2, 2, 2), np.zeros(2, np.float32), np.ones(2, np.float32),
       rpos(2), r(2)),
      grad=(0, 3, 4), op_types=["batch_norm_train"], name="batch_norm_train"),
    C(lambda x, m, v, w, b: F.batch_norm(x, m, v, weight=w, bias=b,
                                         training=False),
      (r(3, 2, 2, 2), r(2, lo=-0.2, hi=0.2), rpos(2, lo=0.5, hi=1.5),
       rpos(2), r(2)),
      grad=(0, 3, 4), op_types=["batch_norm_infer"], name="batch_norm_infer"),
    C(lambda q, k, v: F.scaled_dot_product_attention(q, k, v),
      (r(1, 3, 2, 4), r(1, 3, 2, 4), r(1, 3, 2, 4)),
      grad=(0, 1, 2), op_types=["scaled_dot_product_attention"],
      name="sdpa"),
    C(lambda lp, lab: F.ctc_loss(
        lp, lab, paddle.to_tensor(np.array([4, 4], dtype=np.int64)),
        paddle.to_tensor(np.array([2, 1], dtype=np.int64))),
      (r(4, 2, 3), np.array([[1, 2], [2, 0]], dtype=np.int64)),
      grad=(0,), op_types=["ctc_loss", "warpctc"], name="ctc_loss"),
    C(lambda x, l: F.sigmoid_focal_loss(x, l),
      (r(2, 3), rng.uniform(0, 1, (2, 3)).astype("float32").round()),
      grad=(0,), op_types=["sigmoid_focal_loss"], name="sigmoid_focal"),
    C(lambda x, i: extra_ops.max_unpool2d(x, i, kernel_size=2),
      (r(1, 1, 2, 2), np.array([[[[0, 3], [9, 14]]]], dtype=np.int64)),
      grad=(0,), op_types=["unpool"], name="max_unpool2d"),
    C(lambda x, boxes: paddle.vision.ops.roi_align(
        x, boxes, paddle.to_tensor(np.array([2], dtype=np.int32)),
        output_size=2, spatial_scale=1.0),
      (r(1, 1, 4, 4),
       np.array([[0.4, 0.4, 2.6, 2.6], [1.0, 0.6, 3.0, 2.8]],
                dtype=np.float32)),
      grad=(0,), op_types=["roi_align"], name="roi_align"),
    C(lambda theta: F.affine_grid(theta, out_shape=[1, 1, 3, 3],
                                  align_corners=False),
      (r(1, 2, 3),),
      grad=(0,), op_types=["affine_grid"], name="affine_grid"),
    C(lambda x: F.temporal_shift(x, seg_num=2, shift_ratio=0.25),
      (r(2, 4, 2, 2),),
      grad=(0,), op_types=["temporal_shift"], name="temporal_shift"),
    C(lambda x, t: F.cross_entropy(x, t, soft_label=True),
      (r(2, 4), np.array([[0.2, 0.3, 0.4, 0.1], [0.6, 0.1, 0.2, 0.1]],
                         dtype=np.float32)),
      grad=(0,), op_types=["cross_entropy_probs"], name="ce_soft_label"),
    C(lambda x, c: paddle.corrcoef(paddle.stack([x, c])), (r(5), r(5)),
      grad=(0, 1), op_types=["corrcoef"], name="corrcoef"),
    # recurrent cells / nets: fixed module-level weights, FD wrt inputs
    C(lambda x, h, c: _lstm_cell(x, (h, c))[0], (r(2, 3), r(2, 4), r(2, 4)),
      grad=(0, 1, 2), op_types=["lstm_cell"], name="lstm_cell"),
    C(lambda x, h: _gru_cell(x, h)[0], (r(2, 3), r(2, 4)),
      grad=(0, 1), op_types=["gru_cell"], name="gru_cell"),
    C(lambda x, h: _rnn_cell(x, h)[0], (r(2, 3), r(2, 4)),
      grad=(0, 1), op_types=["simple_rnn_cell"], name="simple_rnn_cell"),
    C(lambda x: _lstm_net(x)[0], (r(2, 3, 2),),
      grad=(0,), op_types=["rnn_scan_lstm", "lstm", "cudnn_lstm"],
      name="lstm_net"),
    C(lambda x: _gru_net(x)[0], (r(2, 3, 2),),
      grad=(0,), op_types=["rnn_scan_gru", "gru"], name="gru_net"),
    C(lambda x: _srnn_net(x)[0], (r(2, 3, 2),),
      grad=(0,), op_types=["rnn_scan_simple", "rnn"], name="simple_rnn_net"),
]

GRAD_CASES = MANIP + LINALG + ELEM + ACT + LOSS + NN + TAIL2


@pytest.mark.parametrize(
    "case", GRAD_CASES,
    ids=[f"{i}:{c.name}" for i, c in enumerate(GRAD_CASES)])
def test_grad_case(case):
    run_case(case)
