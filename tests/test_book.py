"""End-to-end "book" tests.

Reference: python/paddle/fluid/tests/book/ — 8 small models trained to a
loss threshold (test_fit_a_line.py, test_recognize_digits.py,
test_word2vec_book.py, test_understand_sentiment.py). Same pattern here:
tiny real trainings with convergence assertions, each exercising a whole
user workflow (dygraph, static, high-level API, and the big-model
abstract-lowering check for BASELINE config 5).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _linreg_data(n=64):
    rs = np.random.RandomState(0)
    X = rs.randn(n, 13).astype("float32")
    w = rs.randn(13, 1).astype("float32")
    return X, X @ w + 0.1


def test_fit_a_line_dygraph():
    """reference: book/test_fit_a_line.py — linear regression to low loss."""
    paddle.seed(0)
    X, Y = _linreg_data()
    model = paddle.nn.Linear(13, 1)
    sgd = paddle.optimizer.SGD(0.03, parameters=model.parameters())
    loss_val = None
    for _ in range(120):
        loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y))
                ** 2).mean()
        loss.backward()
        sgd.step()
        sgd.clear_grad()
        loss_val = float(loss.numpy())
    assert loss_val < 0.05, loss_val


def test_fit_a_line_static_matches_dygraph():
    """Same model under enable_static: per-step losses equal (the dual-
    execution contract, reference dygraph_to_static parity tests)."""
    X, Y = _linreg_data()

    def dygraph_losses():
        with paddle.utils.unique_name.guard():
            paddle.seed(7)
            model = paddle.nn.Linear(13, 1)
            sgd = paddle.optimizer.SGD(0.05, parameters=model.parameters())
        out = []
        for _ in range(5):
            loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y))
                    ** 2).mean()
            loss.backward()
            sgd.step()
            sgd.clear_grad()
            out.append(float(loss.numpy()))
        return out

    def static_losses():
        paddle.static.global_scope().drop_kids()
        with paddle.utils.unique_name.guard():
            paddle.enable_static()
            try:
                main = paddle.static.Program()
                startup = paddle.static.Program()
                with paddle.static.program_guard(main, startup):
                    paddle.seed(7)
                    x = paddle.static.data("x", [-1, 13], "float32")
                    y = paddle.static.data("y", [-1, 1], "float32")
                    model = paddle.nn.Linear(13, 1)
                    loss = ((model(x) - y) ** 2).mean()
                    paddle.optimizer.SGD(0.05).minimize(loss)
                    exe = paddle.static.Executor()
                    exe.run(startup)
                    out = []
                    for _ in range(5):
                        (lv,) = exe.run(main, feed={"x": X, "y": Y},
                                        fetch_list=[loss])
                        out.append(float(np.asarray(lv)))
                    return out
            finally:
                paddle.disable_static()

    np.testing.assert_allclose(static_losses(), dygraph_losses(),
                               rtol=1e-4, atol=1e-6)


def test_recognize_digits_hapi():
    """reference: book/test_recognize_digits.py via the high-level API —
    LeNet on synthetic MNIST-shaped data through Model.fit."""
    paddle.seed(0)
    rs = np.random.RandomState(0)
    X = rs.randn(128, 1, 28, 28).astype("float32")
    # learnable rule: label = quadrant with the largest mean intensity
    q = np.stack([X[:, 0, :14, :14].mean((1, 2)),
                  X[:, 0, :14, 14:].mean((1, 2)),
                  X[:, 0, 14:, :14].mean((1, 2)),
                  X[:, 0, 14:, 14:].mean((1, 2))], 1)
    Y = q.argmax(1).astype("int64")[:, None]

    from paddle_tpu.io import DataLoader, TensorDataset
    ds = TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])
    loader = DataLoader(ds, batch_size=32, shuffle=True)

    net = paddle.nn.Sequential(
        paddle.nn.Flatten(), paddle.nn.Linear(784, 64), paddle.nn.ReLU(),
        paddle.nn.Linear(64, 4))
    model = paddle.Model(net)
    model.prepare(
        paddle.optimizer.Adam(5e-3, parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss(),
        paddle.metric.Accuracy())
    hist = model.fit(loader, epochs=8, verbose=0)
    res = model.evaluate(loader, verbose=0)
    assert res["acc"] > 0.8, res


def test_word2vec_book():
    """reference: book/test_word2vec_book.py — skipgram-ish embedding
    learns co-occurrence (sparse grads + lazy adam)."""
    paddle.seed(0)
    vocab, dim = 40, 16
    rs = np.random.RandomState(1)
    # pairs (w, w+1 mod vocab) are "co-occurring"
    centers = rs.randint(0, vocab, 512)
    contexts = (centers + 1) % vocab
    emb_in = paddle.to_tensor(
        (0.1 * rs.randn(vocab, dim)).astype("float32"),
        stop_gradient=False)
    emb_out = paddle.to_tensor(
        (0.1 * rs.randn(vocab, dim)).astype("float32"),
        stop_gradient=False)
    opt = paddle.optimizer.Adam(0.05, parameters=[emb_in, emb_out],
                                lazy_mode=True)
    first = last = None
    for i in range(40):
        vi = F.embedding(paddle.to_tensor(centers), emb_in, sparse=True)
        scores = paddle.matmul(vi, emb_out, transpose_y=True)
        loss = F.cross_entropy(scores, paddle.to_tensor(contexts))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
        last = float(loss.numpy())
    assert last < first * 0.2, (first, last)


@pytest.mark.slow
def test_gpt3_1p3b_lowering_config5():
    """BASELINE config 5: GPT-3 1.3B with tp+ZeRO shardings LOWERS to a
    partitioned StableHLO module on an 8-device mesh — abstract tracing
    only (jax.eval_shape-style), no weight materialization, so the test
    proves the sharded program construction handles the real scale."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig.gpt3_1p3b()
    n_params_expected = 1.2e9
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "tp"))

    h, L, V, T = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.max_seq_len)

    def abstract_params():
        p = {"wte": jax.ShapeDtypeStruct((V, h), jnp.bfloat16),
             "wpe": jax.ShapeDtypeStruct((T, h), jnp.bfloat16)}
        for i in range(L):
            p[f"b{i}.qkv"] = jax.ShapeDtypeStruct((h, 3 * h), jnp.bfloat16)
            p[f"b{i}.o"] = jax.ShapeDtypeStruct((h, h), jnp.bfloat16)
            p[f"b{i}.up"] = jax.ShapeDtypeStruct((h, 4 * h), jnp.bfloat16)
            p[f"b{i}.down"] = jax.ShapeDtypeStruct((4 * h, h), jnp.bfloat16)
        return p

    def shardings(p):
        out = {}
        for k, v in p.items():
            if k.endswith(".qkv") or k.endswith(".up") or k == "wte":
                out[k] = NamedSharding(mesh, P(None, "tp")
                                       if v.shape[0] != V
                                       else P("tp", None))
            elif k.endswith(".o") or k.endswith(".down"):
                out[k] = NamedSharding(mesh, P("tp", None))
            else:
                out[k] = NamedSharding(mesh, P())
        return out

    def fwd(params, ids):
        x = params["wte"][ids] + params["wpe"][None, :ids.shape[1]]
        for i in range(L):
            q = x @ params[f"b{i}.qkv"]
            x = x + q[..., :h]
            x = x + jax.nn.gelu(x @ params[f"b{i}.up"]) @ params[f"b{i}.down"]
        return (x @ params["wte"].T).astype(jnp.float32).sum()

    p = abstract_params()
    n_params = sum(int(np.prod(v.shape)) for v in p.values())
    assert n_params > n_params_expected, n_params
    ids = jax.ShapeDtypeStruct((8, T), jnp.int32)
    lowered = jax.jit(
        jax.grad(fwd), in_shardings=(shardings(p), NamedSharding(
            mesh, P("dp", None)))).lower(p, ids)
    txt = lowered.as_text()
    assert "stablehlo" in txt or "module" in txt
    assert "sharding" in txt  # GSPMD annotations made it into the module

def test_dataset_zoo_api_surface():
    """All reference dataset classes exist, iterate, and have the right
    item structure (synthetic fallbacks; reference: text/datasets/*,
    vision/datasets/*)."""
    from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                                 UCIHousing, WMT14, WMT16)
    from paddle_tpu.vision.datasets import (Cifar10, Flowers, MNIST,
                                            VOC2012)

    imdb = Imdb(mode="test")
    doc, label = imdb[0] if isinstance(imdb[0], tuple) else (imdb.docs[0],
                                                             imdb.labels[0])
    assert len(imdb) > 0

    ng = Imikolov(window_size=5)
    assert len(ng[0]) == 5

    ml = Movielens()
    u, m, r = ml[0]
    assert u.dtype == np.int64 and r.dtype == np.float32

    srl = Conll05st()
    words, pred, labels = srl[0]
    assert words.shape == labels.shape and pred.shape == (1,)

    for cls in (WMT14, WMT16):
        wm = cls(mode="train")
        s, t, tn = wm[0]
        assert t.shape == tn.shape and t[0] == wm.BOS and tn[-1] == wm.EOS

    fl = Flowers(mode="test")
    img, y = fl[0]
    assert img.shape == (3, 64, 64) and 0 <= int(y) < Flowers.NUM_CLASSES

    voc = VOC2012(mode="test")
    img, mask = voc[0]
    assert mask.shape == (64, 64) and mask.max() < VOC2012.NUM_CLASSES

    uh = UCIHousing()
    feat, target = uh[0]
    assert feat.shape[-1] == 13
